"""Ingest drain-rate benchmark: ops/s through the real paged pull loop.

Builds a library on node A with a large op backlog (default 120k ops:
tag creates + per-field updates), pairs a FRESH node B over real TCP,
and times the pairing backfill — the responder's pull loop paging
GetOperations at 1000 ops/request through the ingest state machine
(the reference pages at the same size, core/src/p2p/sync/mod.rs:403).

Prints one JSON line: {"metric": "sync_ingest_ops_per_sec", ...}.

Usage: python tools/sync_bench.py [n_ops]
       python tools/sync_bench.py --encode [n_ops]

--encode runs the op-log ENCODE+WRITE micro-benchmark instead: the
same identifier-shaped op specs appended through (a) the per-op row
format and (b) the page-level blob format (native encoder when the
C++ plane is built, Python fragment fallback otherwise), plus the
pure encode cost of both encoders — the before/after artifact for the
blob op-log work, so the row-vs-blob claim never rests on a README
anecdote.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spacedrive_tpu.node import Node  # noqa: E402


def build_backlog(lib, n_ops: int) -> int:
    """Write ~n_ops ops locally: tag creates + name updates, in 1000-op
    transactions (the shape a long-offline peer accumulates)."""
    sync = lib.sync
    total = 0
    while total < n_ops:
        batch = min(1000, n_ops - total)
        ops = []
        rows = []
        for _ in range((batch + 1) // 2):
            pub = os.urandom(16)
            ops.extend(sync.shared_create("tag", pub, {"name": "t"}))
            ops.append(sync.shared_update("tag", pub, "name", "t2"))
            rows.append((pub, "t2"))
        with sync.write_ops(ops) as conn:
            conn.executemany(
                "INSERT INTO tag (pub_id, name) VALUES (?, ?)", rows)
        total += len(ops)
    return total


async def main(n_ops: int) -> None:
    tmp = tempfile.mkdtemp(prefix="sync-bench-")
    a = Node(os.path.join(tmp, "a"))
    b = Node(os.path.join(tmp, "b"))
    await a.start()
    await b.start()
    lib_a = a.create_library("bench")
    total = build_backlog(lib_a, n_ops)

    await a.start_p2p(host="127.0.0.1", enable_discovery=False)
    port_b = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
    b.p2p.on_pairing_request = lambda peer, info: True

    t0 = time.perf_counter()
    assert await a.p2p.pair("127.0.0.1", port_b, lib_a)
    lib_b = b.libraries.list()[0]

    def count_b() -> int:
        return lib_b.db.query_one(
            "SELECT COUNT(*) AS n FROM shared_operation")["n"]

    last = -1
    while True:
        await asyncio.sleep(0.25)
        n = count_b()
        if n >= total:
            break
        if n == last:
            # stalled? poke the originator again (a dropped announce
            # must not hang the bench)
            a.p2p.networked.originate_soon(lib_a)
        last = n
    dt = time.perf_counter() - t0
    rows = lib_b.db.query_one("SELECT COUNT(*) AS n FROM tag")["n"]
    print(json.dumps({
        "metric": "sync_ingest_ops_per_sec",
        "value": round(total / dt, 1),
        "unit": "ops/s",
        "ops": total,
        "seconds": round(dt, 2),
        "pages": -(-total // 1000),
        "replica_tag_rows": rows,
    }))
    await a.shutdown()
    await b.shutdown()


def encode_bench(n_ops: int) -> None:
    """Row-format vs blob-format op-log append, same spec stream."""
    import uuid

    from spacedrive_tpu import native
    from spacedrive_tpu.store.db import Database
    from spacedrive_tpu.sync import opblob
    from spacedrive_tpu.sync.crdt import pack_value, uuid4_bytes_batch
    from spacedrive_tpu.sync.manager import SyncManager

    tmp = tempfile.mkdtemp(prefix="sync-encode-bench-")

    def mk(name: str) -> SyncManager:
        db = Database(os.path.join(tmp, name))
        pub = uuid.uuid4().bytes
        db.insert("instance", {
            "pub_id": pub, "identity": b"", "node_id": b"",
            "node_name": "bench", "node_platform": 0,
            "last_seen": 0, "date_created": 0})
        return SyncManager(db, pub)

    # The identifier's link shape: one multi-field update per file.
    chunk = 4096
    pubs = [os.urandom(16) for _ in range(chunk)]
    specs = [(p, "u:cas_id+object_id", None, None,
              {"cas_id": os.urandom(8).hex(), "object_id": os.urandom(16)})
             for p in pubs]
    n_chunks = max(1, n_ops // chunk)

    def run(mgr: SyncManager, solo: bool) -> float:
        mgr._solo = solo  # False forces the per-op row format
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            with mgr.db.tx() as conn:
                mgr.bulk_shared_ops(conn, "file_path", specs)
        return n_chunks * chunk / (time.perf_counter() - t0)

    rows_ops_s = run(mk("rows.db"), solo=False)
    blob_ops_s = run(mk("blob.db"), solo=True)

    # Pure encode cost, native vs Python fallback (byte-identical).
    stamps = list(range(1 << 61, (1 << 61) + chunk))
    op_ids = uuid4_bytes_batch(chunk)
    vals = [pack_value(s[4]) for s in specs]
    encode_only = {}
    reps = max(1, n_chunks // 2)
    if native.available():
        t0 = time.perf_counter()
        for _ in range(reps):
            native.encode_ops(stamps, pubs, "u:cas_id+object_id",
                              op_ids, vals)
        encode_only["native"] = round(
            reps * chunk / (time.perf_counter() - t0), 1)
    t0 = time.perf_counter()
    for _ in range(reps):
        opblob.encode_uniform_py(stamps, pubs, "u:cas_id+object_id",
                                 op_ids, vals)
    encode_only["python"] = round(
        reps * chunk / (time.perf_counter() - t0), 1)

    print(json.dumps({
        "metric": "oplog_encode_write_ops_per_sec",
        "unit": "ops/s",
        "ops": n_chunks * chunk,
        "chunk": chunk,
        "rows_format": round(rows_ops_s, 1),
        "blob_format": round(blob_ops_s, 1),
        "blob_vs_rows": round(blob_ops_s / rows_ops_s, 2),
        "native_encoder": native.available(),
        "encode_only_ops_per_sec": encode_only,
    }))


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if a != "--encode"]
    n = int(args[0]) if args else 120_000
    if "--encode" in sys.argv[1:]:
        encode_bench(n)
    else:
        asyncio.run(main(n))
