"""Ingest drain-rate benchmark: ops/s through the real paged pull loop.

Builds a library on node A with a large op backlog (default 120k ops:
tag creates + per-field updates), pairs a FRESH node B over real TCP,
and times the pairing backfill — the responder's pull loop paging
GetOperations at 1000 ops/request through the ingest state machine
(the reference pages at the same size, core/src/p2p/sync/mod.rs:403).

Prints one JSON line: {"metric": "sync_ingest_ops_per_sec", ...}.

Usage: python tools/sync_bench.py [n_ops]
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spacedrive_tpu.node import Node  # noqa: E402


def build_backlog(lib, n_ops: int) -> int:
    """Write ~n_ops ops locally: tag creates + name updates, in 1000-op
    transactions (the shape a long-offline peer accumulates)."""
    sync = lib.sync
    total = 0
    while total < n_ops:
        batch = min(1000, n_ops - total)
        ops = []
        rows = []
        for _ in range((batch + 1) // 2):
            pub = os.urandom(16)
            ops.extend(sync.shared_create("tag", pub, {"name": "t"}))
            ops.append(sync.shared_update("tag", pub, "name", "t2"))
            rows.append((pub, "t2"))
        with sync.write_ops(ops) as conn:
            conn.executemany(
                "INSERT INTO tag (pub_id, name) VALUES (?, ?)", rows)
        total += len(ops)
    return total


async def main(n_ops: int) -> None:
    tmp = tempfile.mkdtemp(prefix="sync-bench-")
    a = Node(os.path.join(tmp, "a"))
    b = Node(os.path.join(tmp, "b"))
    await a.start()
    await b.start()
    lib_a = a.create_library("bench")
    total = build_backlog(lib_a, n_ops)

    await a.start_p2p(host="127.0.0.1", enable_discovery=False)
    port_b = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
    b.p2p.on_pairing_request = lambda peer, info: True

    t0 = time.perf_counter()
    assert await a.p2p.pair("127.0.0.1", port_b, lib_a)
    lib_b = b.libraries.list()[0]

    def count_b() -> int:
        return lib_b.db.query_one(
            "SELECT COUNT(*) AS n FROM shared_operation")["n"]

    last = -1
    while True:
        await asyncio.sleep(0.25)
        n = count_b()
        if n >= total:
            break
        if n == last:
            # stalled? poke the originator again (a dropped announce
            # must not hang the bench)
            a.p2p.networked.originate_soon(lib_a)
        last = n
    dt = time.perf_counter() - t0
    rows = lib_b.db.query_one("SELECT COUNT(*) AS n FROM tag")["n"]
    print(json.dumps({
        "metric": "sync_ingest_ops_per_sec",
        "value": round(total / dt, 1),
        "unit": "ops/s",
        "ops": total,
        "seconds": round(dt, 2),
        "pages": -(-total // 1000),
        "replica_tag_rows": rows,
    }))
    await a.shutdown()
    await b.shutdown()


if __name__ == "__main__":
    asyncio.run(main(int(sys.argv[1]) if len(sys.argv) > 1 else 120_000))
