"""Ingest drain-rate benchmark: ops/s through the real paged pull loop.

Builds a library on node A with a large op backlog (default 120k ops:
tag creates + per-field updates), pairs a FRESH node B over real TCP,
and times the pairing backfill — the responder's pull loop paging
GetOperations at 1000 ops/request through the ingest state machine
(the reference pages at the same size, core/src/p2p/sync/mod.rs:403).

Prints one JSON line: {"metric": "sync_ingest_ops_per_sec", ...}.

Usage: python tools/sync_bench.py [n_ops]
       python tools/sync_bench.py --encode [n_ops]
       python tools/sync_bench.py --full-clone [n_files] [--json out.json]

--encode runs the op-log ENCODE+WRITE micro-benchmark instead: the
same identifier-shaped op specs appended through (a) the per-op row
format and (b) the page-level blob format (native encoder when the
C++ plane is built, Python fragment fallback otherwise), plus the
pure encode cost of both encoders — the before/after artifact for the
blob op-log work, so the row-vs-blob claim never rests on a README
anecdote.

--telemetry (any mode) resets the node-wide metrics registry before
the measured section and embeds its snapshot into the printed/written
artifact — the same counters production serves on GET /metrics, so
BENCH rounds and operators read one source of truth.

--full-clone is the READ/APPLY-side artifact for the clone fast path:
it generates an identifier-shaped library (~2 ops per "file": an
object-create page + a file_path-link page per 4096-file chunk, all
page-level blobs, plus a sprinkle of row-format tag ops so the
interleave path runs), then syncs it to TWO fresh peers in the SAME
run — once through the per-op get_ops/receive_crdt_operations pull
loop, once through the blob pass-through + batched-apply stream — and
asserts byte-identical domain tables before reporting ops/s for both,
pages relayed vs rows exploded, and the speedup. Over real TCP (node
pairing) when the p2p plane's `cryptography` dependency exists;
otherwise the same paged streams run in-process and the artifact says
so (`transport`). --json writes the BENCH_r*-style artifact.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_backlog(lib, n_ops: int) -> int:
    """Write ~n_ops ops locally: tag creates + name updates, in 1000-op
    transactions (the shape a long-offline peer accumulates)."""
    sync = lib.sync
    total = 0
    while total < n_ops:
        batch = min(1000, n_ops - total)
        ops = []
        rows = []
        for _ in range((batch + 1) // 2):
            pub = os.urandom(16)
            ops.extend(sync.shared_create("tag", pub, {"name": "t"}))
            ops.append(sync.shared_update("tag", pub, "name", "t2"))
            rows.append((pub, "t2"))
        # per-BATCH txs are the op-log write shape being measured
        with sync.write_ops(ops) as conn:  # sdlint: ok[tx-shape]
            lib.sync.db.run_many("bench.tag_insert", rows, conn=conn)
        total += len(ops)
    return total


def _maybe_reset_telemetry(on: bool) -> None:
    if on:
        from spacedrive_tpu import sanitize, telemetry
        from spacedrive_tpu.store import sqlaudit

        telemetry.reset()
        # arm the SQL auditor in COUNT mode so the artifact's `sql`
        # stage carries per-statement counts + the tx histogram even
        # on unsanitized bench runs (violations count, never raise);
        # connections created after this point are audited
        if not sqlaudit.armed():
            sqlaudit.arm("count", sanitize.record)


def _maybe_embed_telemetry(out: dict, on: bool) -> dict:
    if on:
        from spacedrive_tpu import telemetry
        from spacedrive_tpu.store import sqlaudit

        out["telemetry"] = telemetry.snapshot()
        # the statement-contract view of the run (top statements by
        # count/rows + per-tx histogram): op-log N+1 regressions gate
        # in the bench artifact (round 16)
        out["sql"] = sqlaudit.stage_summary()
    return out


async def main(n_ops: int, with_telemetry: bool = False) -> None:
    from spacedrive_tpu.node import Node
    from spacedrive_tpu import persist

    # Bench harness: blocking corpus teardown on the (idle) loop
    # at exit is the measured run's own cleanup.
    # sdlint: ok[blocking-async]
    with persist.scratch("bench.workdir") as tmp:
        await _run_ingest(tmp, Node, n_ops, with_telemetry)


async def _run_ingest(tmp: str, Node, n_ops: int,
                      with_telemetry: bool) -> None:
    a = Node(os.path.join(tmp, "a"))
    b = Node(os.path.join(tmp, "b"))
    await a.start()
    await b.start()
    lib_a = a.create_library("bench")
    # Bench setup: the backlog WRITE is the fixture, built before
    # the measured section starts.
    # sdlint: ok[blocking-async]
    total = build_backlog(lib_a, n_ops)
    _maybe_reset_telemetry(with_telemetry)

    await a.start_p2p(host="127.0.0.1", enable_discovery=False)
    port_b = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
    b.p2p.on_pairing_request = lambda peer, info: True

    t0 = time.perf_counter()
    assert await a.p2p.pair("127.0.0.1", port_b, lib_a)
    lib_b = b.libraries.list()[0]

    def count_b() -> int:
        return lib_b.db.run("bench.oplog_row_count")["n"]

    last = -1
    while True:
        await asyncio.sleep(0.25)
        # One tiny COUNT per 250ms sample on the harness's own loop.
        # sdlint: ok[blocking-async]
        n = count_b()
        if n >= total:
            break
        if n == last:
            # stalled? poke the originator again (a dropped announce
            # must not hang the bench)
            a.p2p.networked.originate_soon(lib_a)
        last = n
    dt = time.perf_counter() - t0
    # Post-measurement readback; the clock is stopped.
    # sdlint: ok[blocking-async]
    rows = lib_b.db.run("bench.tag_count")["n"]
    print(json.dumps(_maybe_embed_telemetry({
        "metric": "sync_ingest_ops_per_sec",
        "value": round(total / dt, 1),
        "unit": "ops/s",
        "ops": total,
        "seconds": round(dt, 2),
        "pages": -(-total // 1000),
        "replica_tag_rows": rows,
    }, with_telemetry)))
    await a.shutdown()
    await b.shutdown()


def encode_bench(n_ops: int, with_telemetry: bool = False) -> None:
    """Row-format vs blob-format op-log append, same spec stream."""
    from spacedrive_tpu import native
    from spacedrive_tpu.sync import opblob
    from spacedrive_tpu.sync.crdt import pack_value, uuid4_bytes_batch

    from spacedrive_tpu import persist

    _maybe_reset_telemetry(with_telemetry)
    with persist.scratch("bench.workdir") as tmp:
        _run_encode(tmp, n_ops, with_telemetry, native, opblob,
                    pack_value, uuid4_bytes_batch)


def _run_encode(tmp: str, n_ops: int, with_telemetry: bool, native,
                opblob, pack_value, uuid4_bytes_batch) -> None:
    mk = lambda name: _mk_solo(tmp, name)  # noqa: E731

    # The identifier's link shape: one multi-field update per file.
    chunk = 4096
    pubs = [os.urandom(16) for _ in range(chunk)]
    specs = [(p, "u:cas_id+object_id", None, None,
              {"cas_id": os.urandom(8).hex(), "object_id": os.urandom(16)})
             for p in pubs]
    n_chunks = max(1, n_ops // chunk)

    def run(mgr, solo: bool) -> float:
        mgr._solo = solo  # False forces the per-op row format
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            # per-CHUNK txs are the identify write shape measured
            with mgr.db.tx() as conn:  # sdlint: ok[tx-shape]
                mgr.bulk_shared_ops(conn, "file_path", specs)
        return n_chunks * chunk / (time.perf_counter() - t0)

    rows_ops_s = run(mk("rows"), solo=False)
    blob_ops_s = run(mk("blob"), solo=True)

    # Pure encode cost, native vs Python fallback (byte-identical).
    stamps = list(range(1 << 61, (1 << 61) + chunk))
    op_ids = uuid4_bytes_batch(chunk)
    vals = [pack_value(s[4]) for s in specs]
    encode_only = {}
    reps = max(1, n_chunks // 2)
    if native.available():
        t0 = time.perf_counter()
        for _ in range(reps):
            native.encode_ops(stamps, pubs, "u:cas_id+object_id",
                              op_ids, vals)
        encode_only["native"] = round(
            reps * chunk / (time.perf_counter() - t0), 1)
    t0 = time.perf_counter()
    for _ in range(reps):
        opblob.encode_uniform_py(stamps, pubs, "u:cas_id+object_id",
                                 op_ids, vals)
    encode_only["python"] = round(
        reps * chunk / (time.perf_counter() - t0), 1)

    print(json.dumps(_maybe_embed_telemetry({
        "metric": "oplog_encode_write_ops_per_sec",
        "unit": "ops/s",
        "ops": n_chunks * chunk,
        "chunk": chunk,
        "rows_format": round(rows_ops_s, 1),
        "blob_format": round(blob_ops_s, 1),
        "blob_vs_rows": round(blob_ops_s / rows_ops_s, 2),
        "native_encoder": native.available(),
        "encode_only_ops_per_sec": encode_only,
    }, with_telemetry)))


def _mk_solo(tmp: str, name: str):
    """SyncManager over a fresh library DB with only its own instance
    row — the solo configuration blob writers target."""
    import uuid

    from spacedrive_tpu.store.db import Database
    from spacedrive_tpu.sync.manager import SyncManager

    db = Database(os.path.join(tmp, f"{name}.db"))
    pub = uuid.uuid4().bytes
    db.insert("instance", {
        "pub_id": pub, "identity": b"", "node_id": b"",
        "node_name": name, "node_platform": 0,
        "last_seen": 0, "date_created": 0})
    return SyncManager(db, pub)


def build_clone_library(sync, n_files: int, chunk: int = 4096) -> int:
    """Identifier-shaped solo history: per chunk, one object-create
    blob page + one file_path-link blob page + domain rows, plus one
    row-format tag op per chunk (write_ops) so the clone stream's
    ops/page interleave path runs. Returns total ops written."""
    total = 0
    done = 0
    while done < n_files:
        b = min(chunk, n_files - done)
        opubs = [os.urandom(16) for _ in range(b)]
        fpubs = [os.urandom(16) for _ in range(b)]
        tag_pub = os.urandom(16)
        ops = sync.shared_create("tag", tag_pub, {"name": f"t{done}"})
        # per-BATCH txs mirror the identifier's commit groups
        with sync.write_ops(ops) as conn:  # sdlint: ok[tx-shape]
            sync.db.insert("tag", {"pub_id": tag_pub,
                                   "name": f"t{done}"}, conn=conn)
        total += 1
        cas_ids = [os.urandom(8).hex() for _ in range(b)]
        with sync.db.tx() as conn:  # sdlint: ok[tx-shape] same per-batch shape
            total += sync.bulk_shared_ops(conn, "object", [
                (p, "c", None, None, {"kind": 5, "date_created": done + i})
                for i, p in enumerate(opubs)])
            sync.db.run_many(
                "identifier.object_insert",
                [(p, 5, done + i) for i, p in enumerate(opubs)],
                conn=conn)
            total += sync.bulk_shared_ops(conn, "file_path", [
                (fp, "u:cas_id+object_id", None, None,
                 {"cas_id": c, "object_id": op})
                for fp, op, c in zip(fpubs, opubs, cas_ids)])
            sync.db.run_many(
                "bench.file_path_insert",
                [(fp, f"f{done + i}") for i, fp in enumerate(fpubs)],
                conn=conn)
            sync.db.run_many("bench.file_path_link",
                             list(zip(cas_ids, opubs, fpubs)), conn=conn)
        done += b
    return total


def _domain_digest(mgr) -> str:
    """Order-independent digest of the synced domain tables, FK edges
    resolved back to pub ids (local row ids legitimately differ)."""
    import hashlib

    h = hashlib.sha256()
    for row in sorted(
        (r["pub_id"].hex(), r["kind"], r["date_created"], r["note"])
        for r in mgr.db.run("bench.objects_digest")):
        h.update(repr(row).encode())
    for row in sorted(
        (r["pub_id"].hex(), r["cas_id"],
         r["opub"].hex() if r["opub"] else None)
        for r in mgr.db.run("bench.paths_digest")):
        h.update(repr(row).encode())
    for row in sorted((r["pub_id"].hex(), r["name"]) for r in
                      mgr.db.run("bench.tags_digest")):
        h.update(repr(row).encode())
    return h.hexdigest()


def _drain_per_op(src, dst) -> int:
    """The pre-fast-path pull loop: paged get_ops → per-op batched
    ingest (the same-run comparator)."""
    from spacedrive_tpu.sync.manager import GetOpsArgs

    applied = 0
    while True:
        clocks = dict(dst.timestamps)
        clocks[dst.instance] = max(dst.clock.last,
                                   clocks.get(dst.instance, 0))
        page = src.get_ops(GetOpsArgs(clocks=list(clocks.items()),
                                      count=1000))
        page = [op for op in page if op.instance != dst.instance]
        if not page:
            return applied
        # the pull loop's per-PAGE ingest tx is the protocol unit
        n, errs = dst.receive_crdt_operations(page)  # sdlint: ok[tx-shape]
        assert not errs, errs[:3]
        applied += n


def _drain_clone(src, dst) -> dict:
    """The clone fast path, in-process: blob pass-through stream +
    batched fresh-peer apply, then the per-op row tail."""
    applied = pages = fallback = ops_frames = 0
    clocks = [(dst.instance, max(dst.clock.last, 0))]
    for kind, item in src.iter_clone_stream(clocks):
        if kind == "ops":
            n, errs = dst.receive_crdt_operations(item)  # sdlint: ok[tx-shape] per-page protocol unit
            assert not errs, errs[:3]
            applied += n
            ops_frames += 1
        else:
            n, errs, fast = dst.receive_blob_pages([item])  # sdlint: ok[tx-shape] per-page protocol unit
            assert not errs, errs[:3]
            applied += n
            pages += 1 if fast else 0
            fallback += 0 if fast else 1
    applied += _drain_per_op(src, dst)
    return {"applied": applied, "fast_pages": pages,
            "fallback_pages": fallback, "ops_frames": ops_frames}


async def _full_clone_tcp(tmp: str, n_files: int) -> dict:
    """Real-TCP variant: node A holds the library, two fresh nodes pull
    it through pairing — B with pass-through on, C with it forced off
    (the same-run per-op comparator)."""
    from spacedrive_tpu.node import Node

    a = Node(os.path.join(tmp, "a"))
    await a.start()
    lib_a = a.create_library("clone-bench")
    total = build_clone_library(lib_a.sync, n_files)
    await a.start_p2p(host="127.0.0.1", enable_discovery=False)

    async def pull_into(name: str, passthrough: bool) -> dict:
        node = Node(os.path.join(tmp, name))
        await node.start()
        port = await node.start_p2p(host="127.0.0.1",
                                    enable_discovery=False)
        node.p2p.on_pairing_request = lambda peer, info: True
        os.environ["SDTPU_CLONE_PASSTHROUGH"] = \
            "on" if passthrough else "off"
        t0 = time.perf_counter()
        assert await a.p2p.pair("127.0.0.1", port, lib_a)
        lib = node.libraries.list()[0]

        def count() -> int:
            return lib.db.run("bench.oplog_total")["n"]

        last = -1
        while True:
            await asyncio.sleep(0.25)
            n = count()
            if n >= total:
                break
            if n == last:
                a.p2p.networked.originate_soon(lib_a)
            last = n
        dt = time.perf_counter() - t0
        digest = _domain_digest(lib.sync)
        await node.shutdown()
        return {"seconds": dt, "ops_per_sec": total / dt,
                "digest": digest}

    per_op = await pull_into("c", passthrough=False)
    fast = await pull_into("b", passthrough=True)
    os.environ.pop("SDTPU_CLONE_PASSTHROUGH", None)
    origin_digest = _domain_digest(lib_a.sync)
    await a.shutdown()
    assert fast["digest"] == per_op["digest"] == origin_digest, \
        "replicas diverged from origin"
    return {"transport": "tcp", "ops": total,
            "per_op": per_op, "fast": fast}


def _full_clone_inproc(tmp: str, n_files: int) -> dict:
    """In-process variant (no `cryptography` in the runtime): the same
    paged streams the wire carries, minus the socket."""
    origin = _mk_solo(tmp, "origin")
    total = build_clone_library(origin, n_files)

    per_op_mgr = _mk_solo(tmp, "per_op")
    per_op_mgr.register_instance(origin.instance)
    t0 = time.perf_counter()
    applied = _drain_per_op(origin, per_op_mgr)
    per_op_dt = time.perf_counter() - t0
    assert applied == total, (applied, total)

    fast_mgr = _mk_solo(tmp, "fast")
    fast_mgr.register_instance(origin.instance)
    t0 = time.perf_counter()
    stats = _drain_clone(origin, fast_mgr)
    fast_dt = time.perf_counter() - t0
    assert stats["applied"] == total, (stats, total)

    d_fast, d_slow, d_origin = (_domain_digest(fast_mgr),
                                _domain_digest(per_op_mgr),
                                _domain_digest(origin))
    assert d_fast == d_slow == d_origin, "replicas diverged from origin"
    return {"transport": "inproc", "ops": total,
            "per_op": {"seconds": per_op_dt,
                       "ops_per_sec": total / per_op_dt},
            "fast": {"seconds": fast_dt, "ops_per_sec": total / fast_dt,
                     **{k: v for k, v in stats.items()
                        if k != "applied"}}}


def _run_clone(tmp: str, n_files: int) -> dict:
    try:
        import cryptography  # noqa: F401 — p2p tunnel dependency
        have_tcp = True
    except ModuleNotFoundError:
        have_tcp = False
    if have_tcp:
        return asyncio.run(_full_clone_tcp(tmp, n_files))
    return _full_clone_inproc(tmp, n_files)


def full_clone_bench(n_files: int, json_out: str = "",
                     with_telemetry: bool = False) -> None:
    from spacedrive_tpu import native, persist

    _maybe_reset_telemetry(with_telemetry)
    with persist.scratch("bench.workdir") as tmp:
        result = _run_clone(tmp, n_files)
    # rows the per-op comparator exploded on the origin's first ingest
    # are gone by now; count from the blob metadata instead
    out = {
        "metric": "sync_full_clone_ops_per_sec",
        "value": round(result["fast"]["ops_per_sec"], 1),
        "unit": "ops/s",
        "n_files": n_files,
        "ops": result["ops"],
        "transport": result["transport"],
        "per_op_ops_per_sec": round(result["per_op"]["ops_per_sec"], 1),
        "fast_vs_per_op": round(result["fast"]["ops_per_sec"]
                                / result["per_op"]["ops_per_sec"], 2),
        "fast_seconds": round(result["fast"]["seconds"], 2),
        "per_op_seconds": round(result["per_op"]["seconds"], 2),
        "pages_relayed": result["fast"].get("fast_pages"),
        "pages_fallback": result["fast"].get("fallback_pages"),
        "rows_exploded_per_op_path": result["ops"],
        "native_decoder": native.available(),
        "domain_tables_identical": True,
    }
    _maybe_embed_telemetry(out, with_telemetry)
    print(json.dumps(out))
    if json_out:
        persist.atomic_write("bench.artifact", json_out,
                             json.dumps(out, indent=1))


if __name__ == "__main__":
    argv = sys.argv[1:]
    json_out = ""
    if "--json" in argv:
        i = argv.index("--json")
        json_out = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    flags = [a for a in argv if a.startswith("--")]
    args = [a for a in argv if not a.startswith("--")]
    with_telemetry = "--telemetry" in flags
    if "--full-clone" in flags:
        full_clone_bench(int(args[0]) if args else 100_000, json_out,
                         with_telemetry)
    elif "--encode" in flags:
        encode_bench(int(args[0]) if args else 120_000, with_telemetry)
    else:
        asyncio.run(main(int(args[0]) if args else 120_000,
                         with_telemetry))
