"""sd_incidents — the incident observatory's postmortem triage CLI.

Reads the black box the incident observatory keeps
(spacedrive_tpu/incidents.py): every bundle is a snapshot-frozen
causal evidence slice (trigger attribution, flight timeline + spans
filtered to implicated traces, log-ring tail, chaos/backoff/timeout/
shed counters, SQL top-statements, health states, flags, capacity
profile) — this tool lists, renders, and diffs them without the
process that produced them.

    python -m tools.sd_incidents --url http://host:port          # list
    python -m tools.sd_incidents --dir DATA/incidents            # offline list
    python -m tools.sd_incidents --show ID  [--url|--dir ...]    # one bundle
    python -m tools.sd_incidents --diff A B [--url|--dir ...]    # two bundles
    python -m tools.sd_incidents --input bundle.json             # validate only
    python -m tools.sd_incidents --json [--out PATH]             # self-check

- `--dir` triages a COPIED store directory (the bundle files are
  self-contained JSON; scp them off a sick node and read them here).
- `--input` validates a stored artifact — a single bundle file, a
  header, or a `{"incidents": [...]}` artifact (CI gating).
- `--json` without `--url` runs the built-in SELF-CHECK: the same
  three synthetic saturations sd_top's gate drives (a shedding
  channel, a slow store write lock, a fired timeout budget) plus one
  exhausted backoff ladder are pushed through a real HealthMonitor
  and a real observatory; the run must freeze exactly FOUR distinct
  bundles, each schema-valid and attributing the right declared
  resource by name, and repeat pressure inside the dedup window must
  collapse into sd_incident_deduped_total instead of new files.
  Non-zero exit on any violation — tier-1 runs this so the capture
  path cannot rot silently, same pattern as `sd_top --json`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fetch_rspc(url: str, path: str, params: dict = None) -> object:
    q = ""
    if params:
        q = "?input=" + urllib.parse.quote(json.dumps(params))
    endpoint = url.rstrip("/") + "/rspc/" + path + q
    with urllib.request.urlopen(endpoint, timeout=30) as resp:
        payload = json.load(resp)
    if not isinstance(payload, dict) or "result" not in payload:
        raise SystemExit(f"no result in response from {endpoint}")
    return payload["result"]


def _load_store(dir_path: str) -> list:
    """Every complete bundle file in a store directory, newest-first
    (the offline half of incidents.list: torn/.tmp files are skipped,
    exactly what boot-time recovery would discard)."""
    bundles = []
    try:
        names = sorted(os.listdir(dir_path))
    except OSError as e:
        raise SystemExit(f"sd_incidents: unreadable {dir_path}: {e}")
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(dir_path, fn), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("bundle") == "incident":
            bundles.append(doc)
    bundles.sort(key=lambda b: -(b.get("ts") or 0))
    return bundles


def _headers(args) -> list:
    from spacedrive_tpu.incidents import bundle_header

    if args.url:
        return _fetch_rspc(args.url, "incidents.list") or []
    return [bundle_header(b) for b in _load_store(args.dir)]


def _bundle(args, bundle_id: str) -> dict:
    if args.url:
        return _fetch_rspc(args.url, "incidents.get",
                           {"id": bundle_id})
    doc = next((b for b in _load_store(args.dir)
                if b.get("id") == bundle_id), None)
    if doc is None:
        raise SystemExit(f"sd_incidents: no bundle {bundle_id!r} "
                         f"in {args.dir}")
    return doc


def _fmt_ts(ts) -> str:
    if not isinstance(ts, (int, float)):
        return "-"
    return time.strftime("%m-%d %H:%M:%S", time.localtime(ts))


def render_list(headers: list, width: int = 120) -> str:
    out = [f"{'ID':<26} {'WHEN':<15} {'KIND':<18} {'SEV':<4} "
           f"{'ACK':<4} RESOURCE — REASON"]
    for h in headers:
        t = h.get("trigger") or {}
        out.append(
            f"{h.get('id', '?'):<26} {_fmt_ts(h.get('ts')):<15} "
            f"{t.get('kind', '?'):<18} {t.get('severity', '-'):<4} "
            f"{'yes' if h.get('ack') else 'no':<4} "
            f"{t.get('resource', '?')} — {t.get('reason', '')}"[:width])
    if len(out) == 1:
        out.append("(no incident bundles)")
    return "\n".join(out)


def _flat_counters(counters: dict, prefix: str = "") -> dict:
    """Counter stage → flat {family{labels}: value} for diffing; the
    stage values are family snapshot_value() shapes (scalars for plain
    counters, nested dicts for labeled ones)."""
    flat = {}
    for k, v in sorted((counters or {}).items()):
        key = f"{prefix}{k}"
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            flat[key] = v
        elif isinstance(v, dict):
            flat.update(_flat_counters(v, prefix=f"{key}/"))
    return flat


def render_bundle(b: dict, width: int = 100) -> str:
    """One bundle as a triage page: attribution first, then the
    evidence sections sized, then the loudest counters."""
    t = b.get("trigger") or {}
    node = b.get("node") or {}
    out = [
        f"incident {b.get('id')}  [{t.get('kind')}]  "
        f"sev={t.get('severity')}  "
        f"{'acked' if b.get('ack') else 'OPEN'}",
        f"  at    {_fmt_ts(b.get('ts'))}  on "
        f"{node.get('name') or '?'} ({(node.get('id') or '')[:12]})",
        f"  what  {t.get('subsystem')}/{t.get('resource')}",
        f"  why   {t.get('reason')}"[:width],
    ]
    ev = t.get("evidence") or {}
    if ev:
        out.append("  evidence:")
        for k, v in list(ev.items())[:8]:
            out.append(f"    {k} = {json.dumps(v)[:width - 10]}")
    out.append(
        f"  frozen: {len(b.get('timeline') or [])} timeline events, "
        f"{len(b.get('spans') or [])} spans "
        f"({len(b.get('traces') or [])} traces), "
        f"{len(b.get('logs') or [])} log lines")
    health = b.get("health")
    if isinstance(health, dict):
        states = health.get("states") or {}
        hot = {s: st for s, st in sorted(states.items())
               if st != "ok"}
        out.append(f"  health: {json.dumps(hot) if hot else 'all ok'}")
    sql = b.get("sql_top") or []
    if sql:
        out.append("  sql_top: " + ", ".join(
            f"{s.get('statement')}={s.get('total'):g}"
            for s in sql if isinstance(s, dict)))
    flat = _flat_counters(b.get("counters"))
    loud = sorted(((k, v) for k, v in flat.items() if v),
                  key=lambda kv: -abs(kv[1]))[:10]
    if loud:
        out.append("  counters (loudest):")
        for k, v in loud:
            out.append(f"    {k:<58} {v:g}")
    return "\n".join(out)


def render_diff(a: dict, b: dict, width: int = 100) -> str:
    """Two bundles side by side: the trigger lines, every counter
    family that moved between the freezes, and health-state changes —
    'what got worse between these two postmortems'."""
    out = []
    for tag, doc in (("A", a), ("B", b)):
        t = doc.get("trigger") or {}
        out.append(f"{tag}  {doc.get('id')}  {_fmt_ts(doc.get('ts'))}  "
                   f"[{t.get('kind')}] {t.get('subsystem')}/"
                   f"{t.get('resource')}"[:width])
    fa, fb = (_flat_counters(a.get("counters")),
              _flat_counters(b.get("counters")))
    moved = []
    for k in sorted(set(fa) | set(fb)):
        va, vb = fa.get(k, 0), fb.get(k, 0)
        if va != vb:
            moved.append((k, va, vb))
    out.append("")
    if moved:
        out.append(f"{'COUNTER':<56} {'A':>10} {'B':>10} {'Δ':>10}")
        for k, va, vb in moved:
            out.append(f"{k[:56]:<56} {va:>10g} {vb:>10g} "
                       f"{vb - va:>+10g}")
    else:
        out.append("(no counter movement between the bundles)")
    sa = ((a.get("health") or {}).get("states") or {})
    sb = ((b.get("health") or {}).get("states") or {})
    changed = {s: (sa.get(s, "-"), sb.get(s, "-"))
               for s in sorted(set(sa) | set(sb))
               if sa.get(s) != sb.get(s)}
    if changed:
        out.append("")
        out.append("HEALTH STATES (A -> B):")
        for s, (va, vb) in changed.items():
            out.append(f"  {s:<12} {va} -> {vb}")
    return "\n".join(out)


# -- validation + self-check -------------------------------------------------

def input_problems(doc: object) -> list:
    """Validate a stored artifact: a full bundle file, a bare header,
    a list of either, a `{"incidents": [...]}` artifact body, or a
    BENCH artifact whose `incidents` section is the bench shape
    `{"enabled", "headers", "deduped"}` (load_bench / overlap_bench
    --json output validates directly)."""
    from spacedrive_tpu.incidents import (
        validate_incident_bundle,
        validate_incident_header,
    )

    def one(d, where):
        if not isinstance(d, dict):
            return [f"{where}: not an object"]
        if d.get("bundle") == "incident" or "timeline" in d:
            return [f"{where}: {p}"
                    for p in validate_incident_bundle(d)]
        return [f"{where}: {p}" for p in validate_incident_header(d)]

    if isinstance(doc, dict) and isinstance(doc.get("incidents"), dict) \
            and isinstance(doc["incidents"].get("headers"), list):
        doc = {"incidents": doc["incidents"]["headers"]}
    if isinstance(doc, dict) and isinstance(doc.get("incidents"), list):
        problems = []
        for i, d in enumerate(doc["incidents"]):
            problems.extend(one(d, f"incidents[{i}]"))
        return problems
    if isinstance(doc, list):
        problems = []
        for i, d in enumerate(doc):
            problems.extend(one(d, f"[{i}]"))
        return problems
    return one(doc, "bundle")


def build_self_check() -> dict:
    """Drive the capture path end to end against a real observatory:
    sd_top's three known saturations plus one exhausted backoff
    ladder, then repeat pressure to prove dedup."""
    import shutil
    import tempfile

    from spacedrive_tpu import channels, health, incidents, timeouts
    from spacedrive_tpu.telemetry import (
        STORE_WRITE_LOCK_WAIT_SECONDS,
        TIMEOUTS_FIRED,
    )

    tmp = tempfile.mkdtemp(prefix="sd_incidents_check_")
    monitor = health.HealthMonitor(
        interval_s=0.05, node_id="sd-incidents",
        node_name="sd-incidents")
    obs = incidents.install(
        dir_path=tmp, monitor=monitor, node_id="sd-incidents",
        node_name="sd-incidents")
    if obs is None:
        raise SystemExit("sd_incidents: SDTPU_INCIDENTS is off — the "
                         "self-check needs the observatory")
    try:
        # 1-3: the same seeded trio as sd_top --json (channel shed,
        # store write-lock wait, fired network budget)...
        ch = channels.channel("bench.shed")
        for i in range(2 * ch.capacity):
            ch.put_nowait(i)
        STORE_WRITE_LOCK_WAIT_SECONDS.observe(0.8)
        TIMEOUTS_FIRED.labels(name="p2p.ping").inc()
        time.sleep(0.06)  # a real (if tiny) window for the rates
        monitor.sample()  # -> three health.saturated bundles
        # 4: one exhausted ladder (obs.http: finite max_tries)
        ladder = timeouts.Backoff("obs.http")
        while ladder.next_delay() is not None:
            pass          # -> one backoff.give_up bundle
        # Repeat pressure INSIDE the dedup window: the shedding
        # channel's depth gauge persists so the next sample fires the
        # same fingerprint again, and a second exhausted ladder
        # re-fires obs.http — both must dedup, not write files.
        monitor.sample()
        ladder2 = timeouts.Backoff("obs.http")
        while ladder2.next_delay() is not None:
            pass
        headers = obs.list()
        bundles = [obs.get(h["id"]) for h in headers]
        return {
            "metric": "sd_incidents",
            "source": "self-check",
            "incidents": bundles,
            "deduped": obs.deduped(),
        }
    finally:
        incidents.uninstall()
        shutil.rmtree(tmp, ignore_errors=True)


def self_check_problems(artifact: dict) -> list:
    """Schema + semantic gate over the self-check artifact: exactly
    four distinct bundles, each valid, each attributing the seeded
    fault's declared resource by name, repeats deduped."""
    problems = input_problems(artifact)
    bundles = [b for b in artifact.get("incidents", [])
               if isinstance(b, dict)]
    want = {
        "bench.shed": "health.saturated",
        "store.db.write_lock": "health.saturated",
        "p2p.ping": "health.saturated",
        "obs.http": "backoff.give_up",
    }
    got = {(b.get("trigger") or {}).get("resource"):
           (b.get("trigger") or {}).get("kind") for b in bundles}
    for resource, kind in want.items():
        if got.get(resource) != kind:
            problems.append(
                f"self-check: seeded {resource} not captured as "
                f"{kind} (got {got.get(resource)!r})")
    if len(bundles) != len(want):
        problems.append(
            f"self-check: want exactly {len(want)} bundles, got "
            f"{len(bundles)} — dedup failed or a surprise trigger "
            "fired")
    fps = [b.get("fingerprint") for b in bundles]
    if len(set(fps)) != len(fps):
        problems.append("self-check: duplicate fingerprints across "
                        "bundles — dedup identity is broken")
    deduped = artifact.get("deduped")
    if not isinstance(deduped, dict) or sum(deduped.values()) < 2:
        problems.append(
            "self-check: repeat pressure inside the window did not "
            f"dedup (deduped={deduped!r})")
    for b in bundles:
        if not b.get("counters"):
            problems.append(f"self-check: bundle {b.get('id')} froze "
                            "no counter families")
            break
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Incident bundle triage / artifact gate")
    ap.add_argument("--url", default="", metavar="http://host:port",
                    help="triage a live node over rspc HTTP")
    ap.add_argument("--dir", default="", metavar="PATH",
                    help="triage a (copied) incident store directory")
    ap.add_argument("--show", default="", metavar="ID",
                    help="render one full bundle")
    ap.add_argument("--diff", nargs=2, default=None,
                    metavar=("A", "B"),
                    help="diff two bundles (counter movement, health "
                         "state changes)")
    ap.add_argument("--json", action="store_true",
                    help="emit JSON (without --url/--dir: run the "
                         "built-in self-check; exit 1 on violation)")
    ap.add_argument("--input", default="", metavar="PATH",
                    help="validate an existing bundle/artifact file")
    ap.add_argument("--out", default="", metavar="PATH",
                    help="write the (validated) artifact here")
    args = ap.parse_args(argv)

    if args.input:
        try:
            with open(args.input, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"sd_incidents: unreadable {args.input}: {e}",
                  file=sys.stderr)
            return 1
        problems = input_problems(doc)
        for p in problems:
            print(f"sd_incidents: SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"sd_incidents: valid ({args.input})")
        return 0

    if not args.url and not args.dir:
        if not args.json:
            ap.error("need --url, --dir, --input, or --json")
        artifact = build_self_check()
        problems = self_check_problems(artifact)
        for p in problems:
            print(f"sd_incidents: SCHEMA: {p}", file=sys.stderr)
        if problems:
            print(f"sd_incidents: {len(problems)} violation(s)",
                  file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=1)
            print(f"sd_incidents: wrote {args.out}", file=sys.stderr)
        print(json.dumps(artifact))
        return 0

    if args.diff:
        a, b = (_bundle(args, args.diff[0]), _bundle(args, args.diff[1]))
        print(json.dumps({"a": a, "b": b}) if args.json
              else render_diff(a, b))
        return 0
    if args.show:
        doc = _bundle(args, args.show)
        print(json.dumps(doc) if args.json else render_bundle(doc))
        return 0
    headers = _headers(args)
    if args.json:
        artifact = {"metric": "sd_incidents",
                    "source": args.url or args.dir,
                    "incidents": headers}
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=1)
        print(json.dumps(artifact))
        return 0
    print(render_list(headers))
    return 0


if __name__ == "__main__":
    sys.exit(main())
