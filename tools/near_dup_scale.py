"""Near-dup at scale: 1M-digest LSH validation with measured recall.

BASELINE.json config 4/5 requires near-dup search beyond the ~100k
exact-all-pairs ceiling (SURVEY.md §7 hard-part 4). This tool validates
the production path (ops/hamming.near_dup_pairs_lsh — the exact code the
NearDupDetectorJob compare step runs past ALL_PAIRS_LIMIT) on synthetic
64-bit pHashes with planted near-dups:

1. N random digests + P planted pairs at Hamming distance ≤ threshold.
2. Run the LSH pipeline; measure wall time and planted-pair recall.
3. On a 100k subset, also run the exact tiled all-pairs and report
   LSH-vs-exact recall (ground truth, not just planted).

    python tools/near_dup_scale.py --n 1000000 [--planted 5000]

Prints one JSON line per stage.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # PYTHONPATH breaks axon

import numpy as np  # noqa: E402


def make_digests(n: int, planted: int, threshold: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    digests = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32)
    # Plant pairs: copy row i to row j with ≤ threshold flipped bits.
    # src and dst are drawn as ONE disjoint sample: a dst that doubled
    # as another pair's src would be overwritten after being copied
    # (chained overwrite), silently invalidating the earlier plant and
    # capping measurable recall below 1.0.
    both = rng.choice(n, size=2 * planted, replace=False)
    src, dst = both[:planted], both[planted:]
    flips = rng.integers(0, threshold + 1, size=len(src))
    digests[dst] = digests[src]
    for k in range(len(src)):
        bits = rng.choice(64, size=flips[k], replace=False)
        for b in bits:
            digests[dst[k], b // 32] ^= np.uint32(1) << np.uint32(b % 32)
    pairs = {(min(a, b), max(a, b)) for a, b in zip(src.tolist(),
                                                   dst.tolist())}
    return digests, pairs


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--planted", type=int, default=5000)
    ap.add_argument("--threshold", type=int, default=10)
    ap.add_argument("--subset", type=int, default=100_000)
    args = ap.parse_args()

    from spacedrive_tpu.ops.hamming import (
        near_dup_pairs_device, near_dup_pairs_lsh)

    digests, planted = make_digests(args.n, args.planted, args.threshold)

    def recall_of(pairs) -> float:
        s = set(pairs)
        return (sum(1 for p in planted if p in s) / len(planted)
                if planted else 1.0)

    # Production path: exact two-pass device sweep at full N.
    t0 = time.perf_counter()
    exact = near_dup_pairs_device(digests, args.threshold)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "stage": "exact_device", "n": args.n, "seconds": round(dt, 2),
        "digests_per_sec": round(args.n / dt, 1),
        "pairs_found": len(exact),
        "planted": len(planted),
        "planted_recall": round(recall_of(exact), 4),
    }), flush=True)

    # CPU LSH fallback: record its honest (lossy) recall + runtime.
    t0 = time.perf_counter()
    lsh = near_dup_pairs_lsh(digests, args.threshold)
    dt = time.perf_counter() - t0
    exact_set = set(exact)
    print(json.dumps({
        "stage": "lsh_fallback", "n": args.n, "seconds": round(dt, 2),
        "pairs_found": len(lsh),
        "planted_recall": round(recall_of(lsh), 4),
        "recall_vs_exact": round(
            len(exact_set & set(lsh)) / len(exact_set), 4)
        if exact_set else 1.0,
    }), flush=True)


if __name__ == "__main__":
    main()
