"""Kernel-ceiling probe: where does the CAS kernel's last 5x go?

VERDICT r2 item 6: vpu_utilization_est ~0.2 — either lift it past 0.35
or publish the measured breakdown of why ~0.2 is the ceiling on this
chip. This sweep times the production kernel (ops/blake3_jax
_blake3_impl_best — the Pallas chunk-stage kernel on TPU) across batch
sizes and chain lengths with the scan-chained single-sync methodology
(per-call walls measure tunnel RPC, not the kernel):

- if throughput grows with B or ITERS, per-dispatch/per-scan overhead
  is still being amortized (attackable);
- if it is flat, the sustained rate IS the kernel's pipeline rate and
  the gap to the 5e12 ops/s VPU estimate is instruction mix + VMEM
  residency, not dispatch (documented ceiling).

    python tools/kernel_ceiling.py [--quick]

Prints one JSON line per (B, ITERS) config. Never run concurrently
with another TPU process (single-client tunnel).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np  # noqa: E402

OPS_PER_FILE = (57 * 16 + 56) * 1240  # ALU ops: round-4 static mix
# (1,232 G-function ops + 8-xor output fold per compression; bench.py basis)
VPU_OPS_EST = 5e12


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    from spacedrive_tpu.ops import blake3_jax as bj

    configs = ([(16384, 10), (16384, 30)] if args.quick else
               [(4096, 10), (16384, 10), (16384, 30), (32768, 10)])
    rng = np.random.default_rng(0)
    for B, iters in configs:
        payloads = rng.integers(0, 256, size=(B, 57344), dtype=np.uint8)
        sizes = rng.integers(200_000, 5_000_000, size=B).astype(np.uint64)
        words, lengths = bj.build_cas_messages(payloads, sizes)

        @jax.jit
        def looped(w, l, _iters=iters, _B=B):
            def body(acc, _):
                out = bj._blake3_impl_best(
                    w, l | (acc[0, 0] & 1).astype(l.dtype))
                return out, None
            acc, _ = lax.scan(body, jnp.zeros((_B, 8), jnp.uint32),
                              None, length=_iters)
            return acc

        w = jax.device_put(words)
        l = jax.device_put(lengths)
        t0 = time.perf_counter()
        np.asarray(looped(w, l))  # compile + warm + full fetch
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = looped(w, l)
            np.asarray(out)  # full (small) fetch = the only real sync
            best = min(best, (time.perf_counter() - t0) / iters)
        fps = B / best
        print(json.dumps({
            "B": B, "iters": iters,
            "files_per_sec": round(fps, 1),
            "per_dispatch_ms": round(best * 1000, 2),
            "compile_s": round(compile_s, 1),
            "vpu_utilization_est": round(fps * OPS_PER_FILE / VPU_OPS_EST,
                                         3),
        }), flush=True)


if __name__ == "__main__":
    main()
