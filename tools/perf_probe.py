"""Kernel timing probe: chained in-jit loops that survive the tunnel.

On the axon-tunneled bench TPU, per-call wall timing is useless: each
dispatch pays a multi-ms RPC, `block_until_ready` does not actually
block, and a single sync costs up to ~100 ms. The only trustworthy
device-time measurement is to run N kernel executions INSIDE one jitted
program, serialized by a loop-carried data dependency XLA cannot fold
away (`lengths | (acc & 1)` — value-unknown at compile time), and time
the whole program with one D2H sync at the end.

Usage (run from the repo root, real chip):
    python tools/perf_probe.py

Prints files/s for: the AVX2 C++ plane (the honest CPU baseline), the
jnp scan path, the Pallas kernel, plus H2D link bandwidth and the
steady-state overlapped-pipeline estimate.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root; PYTHONPATH
# breaks the axon TPU plugin's interpreter-start registration, so the
# repo root must be injected here instead.

import numpy as np  # noqa: E402

B = 2048
ITERS = 20
MSG_BYTES = 57352  # 8-byte size prefix + 57,344 sampled bytes


def make_batch():
    from spacedrive_tpu.ops import blake3_jax as bj

    rng = np.random.default_rng(0)
    payloads = rng.integers(0, 256, size=(B, 57344), dtype=np.uint8)
    sizes = rng.integers(200_000, 50_000_000, size=B).astype(np.uint64)
    words, lengths = bj.build_cas_messages(payloads, sizes)
    return payloads, sizes, words, lengths


def native_files_per_sec(payloads, sizes) -> float:
    from spacedrive_tpu import native

    if not native.available():
        return 0.0
    lens = np.full(B, payloads.shape[1], np.int32)
    native.blake3_many(payloads[:64], lens[:64], sizes[:64])  # warm pool
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        native.blake3_many(payloads, lens, sizes)
    return B * iters / (time.perf_counter() - t0)


def device_loop_timer(body_fn, words, lengths, iters: int = ITERS) -> float:
    """Seconds per body_fn(words, lengths) execution, measured on-device."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    @jax.jit
    def looped(w, l):
        def body(acc, _):
            out = body_fn(w, l | (acc[0, 0] & 1).astype(l.dtype))
            return out, None
        acc, _ = lax.scan(body, jnp.zeros((B, 8), jnp.uint32),
                          None, length=iters)
        return acc

    w = jax.device_put(words)
    l = jax.device_put(lengths)
    r = looped(w, l)
    np.asarray(r.ravel()[0])  # compile + warm; sync via D2H (see module doc)
    t0 = time.perf_counter()
    r = looped(w, l)
    np.asarray(r.ravel()[0])
    return (time.perf_counter() - t0) / iters


def h2d_seconds(words) -> float:
    import jax

    w = jax.device_put(words)
    np.asarray(w.ravel()[0])
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        w = jax.device_put(words)
        np.asarray(w.ravel()[0])
    return (time.perf_counter() - t0) / iters


def main() -> None:
    payloads, sizes, words, lengths = make_batch()

    nat = native_files_per_sec(payloads, sizes)
    print(f"native AVX2 C++ plane: {nat:,.0f} files/s "
          f"({nat * MSG_BYTES / 1e9:.2f} GB/s)")

    from spacedrive_tpu.ops import blake3_jax as bj
    from spacedrive_tpu.ops import blake3_pallas as bp

    t = device_loop_timer(bj._blake3_jnp_jit, words, lengths)
    print(f"jnp scan path: {t*1e3:.2f} ms/batch -> {B/t:,.0f} files/s")

    if bp.supported():
        t = device_loop_timer(bp.blake3_words_pallas, words, lengths)
        print(f"pallas kernel: {t*1e3:.2f} ms/batch -> {B/t:,.0f} files/s "
              f"({B * MSG_BYTES / t / 1e9:.1f} GB/s)")
        th = h2d_seconds(words)
        print(f"H2D: {words.nbytes/th/1e9:.2f} GB/s "
              f"({th*1e3:.0f} ms/batch)")
        steady = B / max(t, th)
        print(f"overlapped-pipeline estimate: {steady:,.0f} files/s")
    else:
        print("pallas: unsupported on this backend")


if __name__ == "__main__":
    main()
