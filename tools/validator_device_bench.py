"""Real-chip jax-validator workload figure (VERDICT r3 item 9).

Runs the ObjectValidatorJob with backend="jax" — each file's chunk
chain streamed through StreamingShardedChecksum on the LOCAL device
mesh (one chip on the bench host) — against a small real corpus
through the full job system, and prints one JSON line with files/s and
MB/s. This is the honest single-device long-context-plane number the
virtual-mesh figure in PARITY.md explicitly is not.

Run ALONE (single-client tunnel). Corpus is deliberately small: the
tunneled link makes every window H2D-bound, which is the point — the
figure characterizes this host, not the kernel.

Usage: python tools/validator_device_bench.py [n_files] [file_kb]
       python tools/validator_device_bench.py --kernel [n_files] [file_kb]

--kernel prints the KERNEL-SIDE figure instead (VERDICT r5 weak #5):
the checksum hasher behind checksums_words_batched timed as ITERS
chained executions inside one jit with a loop-carried dependency —
bench.py's CAS methodology, so the number excludes the tunnel RPC +
D2H sync that dominates any per-call wall timing. files/s + GB/s on
whatever device jax resolves (the bench chip on the bench host; the
CPU backend elsewhere, labeled as such).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import sys
import time

# The tunneled bench link moves ~10-20 MB/s on bad days; keep each
# batched dispatch's padded grid in the few-second range (the remote
# worker stalls on minutes-long single transfers). 8 MiB ≈ 32 files at
# 256 KiB — still a 32× RPC amortization over round 4's 1-file
# dispatches.
os.environ.setdefault("SDTPU_VAL_BATCH_BYTES", str(8 << 20))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(n_files: int, file_kb: int) -> None:
    from spacedrive_tpu.locations.manager import (create_location,
                                                  scan_location)
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.objects.validator import ObjectValidatorJob

    from spacedrive_tpu import persist

    # Bench harness: blocking corpus teardown on the (idle) loop
    # at exit is the measured run's own cleanup.
    # sdlint: ok[blocking-async]
    with persist.scratch("bench.workdir") as tmp:
        corpus = os.path.join(tmp, "corpus")
        os.makedirs(corpus)
        rng = random.Random(3)
        total_bytes = 0
        for i in range(n_files):
            data = rng.randbytes(file_kb * 1024)
            with open(os.path.join(corpus, f"f{i}.bin"), "wb") as f:
                f.write(data)
            total_bytes += len(data)

        node = Node(os.path.join(tmp, "data"))
        await node.start()
        lib = node.create_library("valbench")
        loc = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc, backend="native",
                            with_media=False)
        await node.jobs.wait_idle()

        t0 = time.perf_counter()
        jid = await node.jobs.ingest(
            lib, ObjectValidatorJob(location_id=loc, backend="jax", mode="fill"))
        await node.jobs.wait(jid)
        dt = time.perf_counter() - t0
        n_done = lib.db.run("bench.checksum_count")["n"]
        # Same-weather comparator: the round-4 ONE-DISPATCH-PER-FILE path
        # (streaming sequence-sharded windows) on a subset — the tunneled
        # link's throughput swings 100x day to day, so the amortization
        # claim is only honest against the per-file rate measured in the
        # SAME run.
        import glob

        import jax

        from spacedrive_tpu.ops.seqhash import sharded_file_checksum
        from spacedrive_tpu.parallel.mesh import batch_mesh

        mesh = batch_mesh(list(jax.devices())[:1])
        subset = sorted(glob.glob(os.path.join(corpus, "*.bin")))[
            :min(20, n_files)]
        sharded_file_checksum(mesh, subset[0])  # compile outside the timer
        t0 = time.perf_counter()
        for p_ in subset:
            sharded_file_checksum(mesh, p_)
        per_file_dt = (time.perf_counter() - t0) / len(subset)
        per_file_fps = 1.0 / per_file_dt

        print(json.dumps({
            "metric": "validator_jax_device_files_per_sec",
            "value": round(n_done / dt, 2),
            "unit": "files/s",
            "mb_per_sec": round(total_bytes / dt / 1e6, 2),
            "files": n_done,
            "file_kb": file_kb,
            "seconds": round(dt, 2),
            "backend": "jax (batched small-file dispatches + StreamingShardedChecksum for large)",
            "batched_small_files": True,
            "per_file_dispatch_files_per_sec": round(per_file_fps, 2),
            "batch_amortization_x": round((n_done / dt) / per_file_fps, 1),
        }))
        await node.shutdown()


def kernel_figure(n_files: int, file_kb: int, iters: int = 30) -> None:
    """Chained-in-jit throughput of the batched-validator checksum
    kernel (ops/blake3_jax hasher over a checksums_words_batched-shaped
    grid). Mirrors bench.py: ITERS executions chained through lax.scan
    with a loop-carried dependency so per-iteration wall is
    t_fixed/ITERS + t_marginal, best-of-3."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp
    import numpy as np

    from spacedrive_tpu.ops import blake3_jax as bj
    from spacedrive_tpu.ops.blake3_batch import (CHUNK_LEN,
                                                 WORDS_PER_CHUNK,
                                                 digests_to_hex)

    B = n_files
    blob_len = file_kb * 1024
    # The same shared pow2 chunk grid checksums_words_batched packs
    # pages into (equal sizes here: the bench characterizes the kernel,
    # not the padding policy).
    C = max(1, -(-blob_len // CHUNK_LEN))
    C = 1 << (C - 1).bit_length()
    rng = np.random.default_rng(7)
    buf = np.zeros((B, C * CHUNK_LEN), dtype=np.uint8)
    buf[:, :blob_len] = rng.integers(0, 256, size=(B, blob_len),
                                     dtype=np.uint8)
    words = buf.view("<u4").reshape(B, C, WORDS_PER_CHUNK)
    lengths = np.full(B, blob_len, dtype=np.int32)

    @jax.jit
    def looped(w, l):
        def body(acc, _):
            out = bj._blake3_impl_best(
                w, l | (acc[0, 0] & 1).astype(l.dtype))
            return out, None
        acc, _ = lax.scan(body, jnp.zeros((B, 8), jnp.uint32),
                          None, length=iters)
        return acc

    w = jax.device_put(words)
    l = jax.device_put(lengths)
    r = looped(w, l)
    np.asarray(r.ravel()[0])  # compile + warm (block_until_ready lies on axon)
    t = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = looped(w, l)
        np.asarray(r.ravel()[0])
        t = min(t, (time.perf_counter() - t0) / iters)

    # Correctness spot check against the streaming oracle/native plane.
    hexes = digests_to_hex(bj.blake3_words(words, lengths)[:2])
    from spacedrive_tpu import native
    if native.available():
        for i in range(2):
            expect = native.blake3_digest(
                buf[i, :blob_len].tobytes()).hex()
            assert hexes[i] == expect, (i, hexes[i], expect)

    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "validator_kernel_files_per_sec",
        "value": round(B / t, 1),
        "unit": "files/s",
        "gb_per_sec": round(B * blob_len / t / 1e9, 3),
        "files": B,
        "file_kb": file_kb,
        "iters": iters,
        "chunk_grid_C": C,
        "device": f"{dev.platform}:{getattr(dev, 'device_kind', '?')}",
        "methodology": "ITERS chained in one jit (bench.py CAS "
                       "methodology), best-of-3",
    }))


if __name__ == "__main__":
    argv = [a for a in sys.argv[1:] if a != "--kernel"]
    n = int(argv[0]) if argv else 100
    kb = int(argv[1]) if len(argv) > 1 else 256
    if "--kernel" in sys.argv[1:]:
        kernel_figure(n, kb)
    else:
        asyncio.run(run(n, kb))
