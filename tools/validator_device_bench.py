"""Real-chip jax-validator workload figure (VERDICT r3 item 9).

Runs the ObjectValidatorJob with backend="jax" — each file's chunk
chain streamed through StreamingShardedChecksum on the LOCAL device
mesh (one chip on the bench host) — against a small real corpus
through the full job system, and prints one JSON line with files/s and
MB/s. This is the honest single-device long-context-plane number the
virtual-mesh figure in PARITY.md explicitly is not.

Run ALONE (single-client tunnel). Corpus is deliberately small: the
tunneled link makes every window H2D-bound, which is the point — the
figure characterizes this host, not the kernel.

Usage: python tools/validator_device_bench.py [n_files] [file_kb]
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import sys
import tempfile
import time

# The tunneled bench link moves ~10-20 MB/s on bad days; keep each
# batched dispatch's padded grid in the few-second range (the remote
# worker stalls on minutes-long single transfers). 8 MiB ≈ 32 files at
# 256 KiB — still a 32× RPC amortization over round 4's 1-file
# dispatches.
os.environ.setdefault("SDTPU_VAL_BATCH_BYTES", str(8 << 20))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(n_files: int, file_kb: int) -> None:
    from spacedrive_tpu.locations.manager import (create_location,
                                                  scan_location)
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.objects.validator import ObjectValidatorJob

    tmp = tempfile.mkdtemp(prefix="sdtpu-valbench-")
    corpus = os.path.join(tmp, "corpus")
    os.makedirs(corpus)
    rng = random.Random(3)
    total_bytes = 0
    for i in range(n_files):
        data = rng.randbytes(file_kb * 1024)
        with open(os.path.join(corpus, f"f{i}.bin"), "wb") as f:
            f.write(data)
        total_bytes += len(data)

    node = Node(os.path.join(tmp, "data"))
    await node.start()
    lib = node.create_library("valbench")
    loc = create_location(lib, corpus)
    await scan_location(node.jobs, lib, loc, backend="native",
                        with_media=False)
    await node.jobs.wait_idle()

    t0 = time.perf_counter()
    jid = await node.jobs.ingest(
        lib, ObjectValidatorJob(location_id=loc, backend="jax", mode="fill"))
    await node.jobs.wait(jid)
    dt = time.perf_counter() - t0
    n_done = lib.db.query_one(
        "SELECT COUNT(*) AS n FROM file_path "
        "WHERE integrity_checksum IS NOT NULL")["n"]
    # Same-weather comparator: the round-4 ONE-DISPATCH-PER-FILE path
    # (streaming sequence-sharded windows) on a subset — the tunneled
    # link's throughput swings 100x day to day, so the amortization
    # claim is only honest against the per-file rate measured in the
    # SAME run.
    import glob

    import jax

    from spacedrive_tpu.ops.seqhash import sharded_file_checksum
    from spacedrive_tpu.parallel.mesh import batch_mesh

    mesh = batch_mesh(list(jax.devices())[:1])
    subset = sorted(glob.glob(os.path.join(corpus, "*.bin")))[
        :min(20, n_files)]
    sharded_file_checksum(mesh, subset[0])  # compile outside the timer
    t0 = time.perf_counter()
    for p_ in subset:
        sharded_file_checksum(mesh, p_)
    per_file_dt = (time.perf_counter() - t0) / len(subset)
    per_file_fps = 1.0 / per_file_dt

    print(json.dumps({
        "metric": "validator_jax_device_files_per_sec",
        "value": round(n_done / dt, 2),
        "unit": "files/s",
        "mb_per_sec": round(total_bytes / dt / 1e6, 2),
        "files": n_done,
        "file_kb": file_kb,
        "seconds": round(dt, 2),
        "backend": "jax (batched small-file dispatches + StreamingShardedChecksum for large)",
        "batched_small_files": True,
        "per_file_dispatch_files_per_sec": round(per_file_fps, 2),
        "batch_amortization_x": round((n_done / dt) / per_file_fps, 1),
    }))
    await node.shutdown()


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    kb = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    asyncio.run(run(n, kb))
