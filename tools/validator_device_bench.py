"""Real-chip jax-validator workload figure (VERDICT r3 item 9).

Runs the ObjectValidatorJob with backend="jax" — each file's chunk
chain streamed through StreamingShardedChecksum on the LOCAL device
mesh (one chip on the bench host) — against a small real corpus
through the full job system, and prints one JSON line with files/s and
MB/s. This is the honest single-device long-context-plane number the
virtual-mesh figure in PARITY.md explicitly is not.

Run ALONE (single-client tunnel). Corpus is deliberately small: the
tunneled link makes every window H2D-bound, which is the point — the
figure characterizes this host, not the kernel.

Usage: python tools/validator_device_bench.py [n_files] [file_kb]
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(n_files: int, file_kb: int) -> None:
    from spacedrive_tpu.locations.manager import (create_location,
                                                  scan_location)
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.objects.validator import ObjectValidatorJob

    tmp = tempfile.mkdtemp(prefix="sdtpu-valbench-")
    corpus = os.path.join(tmp, "corpus")
    os.makedirs(corpus)
    rng = random.Random(3)
    total_bytes = 0
    for i in range(n_files):
        data = rng.randbytes(file_kb * 1024)
        with open(os.path.join(corpus, f"f{i}.bin"), "wb") as f:
            f.write(data)
        total_bytes += len(data)

    node = Node(os.path.join(tmp, "data"))
    await node.start()
    lib = node.create_library("valbench")
    loc = create_location(lib, corpus)
    await scan_location(node.jobs, lib, loc, backend="native",
                        with_media=False)
    await node.jobs.wait_idle()

    t0 = time.perf_counter()
    jid = await node.jobs.ingest(
        lib, ObjectValidatorJob(location_id=loc, backend="jax", mode="fill"))
    await node.jobs.wait(jid)
    dt = time.perf_counter() - t0
    n_done = lib.db.query_one(
        "SELECT COUNT(*) AS n FROM file_path "
        "WHERE integrity_checksum IS NOT NULL")["n"]
    print(json.dumps({
        "metric": "validator_jax_device_files_per_sec",
        "value": round(n_done / dt, 2),
        "unit": "files/s",
        "mb_per_sec": round(total_bytes / dt / 1e6, 2),
        "files": n_done,
        "file_kb": file_kb,
        "seconds": round(dt, 2),
        "backend": "jax (batched small-file dispatches + StreamingShardedChecksum for large)",
        "batched_small_files": True,
    }))
    await node.shutdown()


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    kb = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    asyncio.run(run(n, kb))
