"""wire_grid — feed EVERY declared message every malformed shape.

The wire registry (spacedrive_tpu/p2p/wire.py) declares, per message,
the exact contract a frame must meet: schema tokens, const
discriminators, version consts, size cap. This harness holds that
contract to account cell by cell: for every declared message it
builds a well-formed CONTROL frame through `wire.pack` and then
derives one mutant per applicable mutation —

- ``drop-required``: the last required/const field removed;
- ``truncate``: everything after the first field dropped (emitted
  only when a required field is among the casualties);
- ``type-flip``: the first typed field replaced with a wrong-typed
  value (a truncated/garbage value for the scalar contracts);
- ``unknown-kind``: the discriminator flipped to a value no
  declaration claims (an out-of-set verdict for values messages);
- ``oversize``: the transport byte count one past the declared cap;
- ``version-skew``: the proto field set to version+1 (the 7
  version-bearing messages).

Every cell asserts REJECT-WITHOUT-CRASH, both ways frames enter:

- `wire.unpack(name, mutant)` must raise a WireError subclass —
  never any other exception, never accept;
- `wire.audit_frame(mutant, ...)` (the armed tunnel-seam auditor)
  must return None and record exactly one violation — of kind
  `proto_skew` for version-skew cells and `size_cap` for oversize
  cells;
- the CONTROL must unpack clean and come back from the auditor with
  a declared name and zero violations.

A new declaration is covered the moment it lands, with zero new grid
code. `--json [PATH|-]` emits the grid as a BENCH-style artifact; the
exit code gates (0 iff every cell passed) so tests/test_wire_grid.py
can wire the full grid into tier-1 — the same shape as
tools/crash_grid.py for the persist seam.

Usage:
    python tools/wire_grid.py [--json [PATH|-]]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# One well-typed sample per schema token — enough to satisfy pack().
_SAMPLES: Dict[str, Any] = {
    "str": "x", "int": 7, "bytes": b"\x01", "bool": True,
    "float": 1.0, "list": [], "dict": {}, "any": "x",
}
# One wrong-typed value per token (bools are refused for int/float by
# the registry itself, so plain swaps suffice).
_FLIPS: Dict[str, Any] = {
    "str": 7, "int": "x", "bytes": 7, "bool": "x",
    "float": "x", "list": 7, "dict": 7,
}


def control_frame(wire, name: str) -> Any:
    """A well-formed frame, built the only sanctioned way."""
    msg = wire.message(name)
    if msg.values is not None:
        return wire.pack(name, value=msg.values[0])
    if msg.binary:
        return wire.pack(name, value=b"\x01")
    required = {f.name: _SAMPLES[f.type] for f in msg.fields
                if f.const is None and not f.optional
                and not f.is_proto}
    return wire.pack(name, **required)


def mutants(wire, name: str,
            control: Any) -> List[Tuple[str, Any, Optional[int]]]:
    """(mutation, frame, nbytes) cells applicable to this message."""
    msg = wire.message(name)
    out: List[Tuple[str, Any, Optional[int]]] = []

    if msg.values is not None:
        out.append(("truncate", control[:-1], None))
        out.append(("type-flip", 3.14, None))
        out.append(("unknown-kind", "__bogus_verdict__", None))
    elif msg.binary:
        out.append(("type-flip", 3.14, None))
    else:
        keys = list(control)
        by_name = {f.name: f for f in msg.fields}
        mandatory = [k for k in keys if not by_name[k].optional]
        if mandatory:
            dropped = dict(control)
            del dropped[mandatory[-1]]
            out.append(("drop-required", dropped, None))
        if len(keys) > 1 and any(not by_name[k].optional
                                 for k in keys[1:]):
            out.append(("truncate", {keys[0]: control[keys[0]]}, None))
        for f in msg.fields:
            if f.name in control and f.const is None \
                    and not f.is_proto and f.type in _FLIPS:
                flipped = dict(control)
                flipped[f.name] = _FLIPS[f.type]
                out.append(("type-flip", flipped, None))
                break
        consts = [f.name for f in msg.fields
                  if f.const is not None and f.name in ("t", "kind")]
        if consts:
            bogus = dict(control)
            for k in consts:
                bogus[k] = "__bogus_kind__"
            out.append(("unknown-kind", bogus, None))
        if any(f.is_proto for f in msg.fields):
            skewed = dict(control)
            for f in msg.fields:
                if f.is_proto:
                    skewed[f.name] = msg.version + 1
            out.append(("version-skew", skewed, None))

    out.append(("oversize", control, msg.size_cap + 1))
    return out


def _violation_counts(wire) -> Dict[str, float]:
    """Per-subkind sd_wire_violations_total values — the grid reads
    the same census production dashboards do."""
    from spacedrive_tpu.telemetry import WIRE_VIOLATIONS

    return {labels["kind"]: metric.value
            for labels, metric in WIRE_VIOLATIONS.samples()
            if labels}


def _still_valid(wire, frame: Any, nbytes: Optional[int]):
    """The declared name a frame legitimately satisfies, if any — a
    mutation can land on ANOTHER valid contract (the status-only
    response envelopes are structurally identical), and the auditor
    is right to pass such a frame."""
    for cand in wire.classify(frame):
        try:
            wire.unpack(cand, frame, size=nbytes)
            return cand
        except wire.WireError:
            continue
    return None


def run_cell(wire, name: str, mutation: Optional[str], frame: Any,
             nbytes: Optional[int], auditable: bool = True) -> Dict:
    """Judge one (message, mutation) cell both ways frames enter."""
    problems: List[str] = []
    before = _violation_counts(wire)

    if mutation is None:                       # control
        try:
            wire.unpack(name, frame, size=nbytes)
        except Exception as e:
            problems.append(f"control frame refused: {e!r}")
        audited = wire.audit_frame(frame, "in", nbytes)
        if audited is None:
            problems.append("auditor rejected the control frame")
        kinds = _delta(before, _violation_counts(wire))
        if kinds:
            problems.append(f"control recorded violations: {kinds}")
    else:
        try:
            wire.unpack(name, frame, size=nbytes)
            problems.append("mutant ACCEPTED by unpack")
        except wire.WireError:
            pass                               # the contract held
        except Exception as e:                 # reject ≠ crash
            problems.append(
                f"mutant CRASHED unpack with non-wire {e!r}")
        audited = None
        try:
            audited = wire.audit_frame(frame, "in", nbytes)
        except Exception as e:
            problems.append(f"mutant CRASHED the auditor: {e!r}")
        kinds = _delta(before, _violation_counts(wire))
        if auditable:
            if audited is not None:
                problems.append(
                    f"auditor passed the mutant as {audited!r}")
            if sum(kinds.values()) != 1:
                problems.append(
                    f"expected exactly one violation, got {kinds}")
            want = {"version-skew": "proto_skew",
                    "oversize": "size_cap"}.get(mutation)
            # exact-subkind assertions only when classification is
            # unambiguous: a status-only envelope matches several
            # declarations, and the auditor reports the most
            # actionable breach among them (skew over size)
            if want and kinds and want not in kinds \
                    and len(wire.classify(frame)) == 1:
                problems.append(
                    f"violation kind(s) {sorted(kinds)}, "
                    f"expected {want!r}")

    return {"message": name, "mutation": mutation or "control",
            "violations": sorted(kinds) if mutation else [],
            "audited": auditable, "problems": problems}


def _delta(before: Dict[str, float],
           after: Dict[str, float]) -> Dict[str, float]:
    return {k: after[k] - before.get(k, 0.0) for k in after
            if after[k] != before.get(k, 0.0)}


def build_cells(wire) -> List[Tuple[str, Optional[str], Any,
                                    Optional[int], bool]]:
    cells = []
    for name in sorted(wire.MESSAGES):
        control = control_frame(wire, name)
        cells.append((name, None, control, 1, True))
        for mutation, frame, nbytes in mutants(wire, name, control):
            auditable = _still_valid(wire, frame, nbytes) is None
            cells.append((name, mutation, frame, nbytes, auditable))
    return cells


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/wire_grid.py",
        description="feed every declared wire message every malformed "
                    "shape; assert reject-without-crash")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH",
                    help="emit the grid as a JSON artifact "
                         "(default '-': stdout)")
    args = ap.parse_args(argv)

    from spacedrive_tpu.p2p import wire

    # Arm the auditor in count mode (the production posture): mutant
    # after mutant flows through the same audit seam the tunnels use,
    # and the grid reads the violation census off the metric.
    wire.arm("count", lambda kind, detail, may_raise: None)

    rounds = []
    try:
        for name, mutation, frame, nbytes, auditable in \
                build_cells(wire):
            rounds.append(run_cell(wire, name, mutation, frame,
                                   nbytes, auditable))
    finally:
        wire.disarm()

    failures = [f"{r['message']}@{r['mutation']}: {p}"
                for r in rounds for p in r["problems"]]
    doc = {
        "metric": "wire_grid",
        "messages": sorted(wire.MESSAGES),
        "cells": len(rounds),
        "mutations": sum(1 for r in rounds
                         if r["mutation"] != "control"),
        # mutants that landed on ANOTHER valid contract: unpack-side
        # assertions only (the auditor is right to pass them)
        "unaudited": [f"{r['message']}@{r['mutation']}"
                      for r in rounds if not r["audited"]],
        "failures": failures,
        "pass": not failures,
        "rounds": rounds,
    }
    if args.json == "-":
        print(json.dumps(doc, indent=1))
    elif args.json:
        from spacedrive_tpu import persist
        persist.atomic_write("bench.artifact", args.json,
                             json.dumps(doc, indent=1))
    summary = (f"wire_grid: {doc['cells']} cells "
               f"({doc['mutations']} mutations) over "
               f"{len(doc['messages'])} messages — "
               + ("PASS" if doc["pass"] else
                  f"{len(failures)} FAILURE(S)"))
    print(summary, file=sys.stderr)
    for f in failures:
        print(f"  {f}", file=sys.stderr)
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
