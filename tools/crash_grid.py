"""crash_grid — SIGKILL the persist seam at EVERY declared edge.

The durability registry (spacedrive_tpu/persist.py) declares, per
artifact, the exact edges one write passes: tmp-open → tmp-partial →
tmp-full → [fsync-file] → renamed. This harness holds that contract to
account the only way that counts: for every (artifact, edge) in the
declared grid it seeds a committed payload A, spawns a CHILD process
that writes payload B with `SDTPU_PERSIST_CRASHPOINT=<name>:<edge>`
exported — the persist crashpoint seam SIGKILLs the child mid-write at
precisely that edge — then runs the artifact's declared recovery and
asserts the survivor is VALID-OR-ABSENT-OF-TEARING:

- killed before the tmp is complete (tmp-open, tmp-partial): the
  committed A must still be there, byte-identical;
- killed with a complete tmp (tmp-full, fsync-file): `atomic`
  artifacts must still read A (residue discarded), `wal` artifacts
  must read B (complete tmp PROMOTED by recover — that is the WAL
  contract);
- killed after the rename (renamed): B, both kinds;
- after recovery, zero `*.tmp` residue remains;
- a CONTROL child with no crashpoint set must exit 0 and commit B.

A failure in any cell names the artifact, the edge, and what was
found instead. `--json [PATH|-]` emits the whole grid as a BENCH-style
artifact (written through the persist seam, naturally); the exit code
gates (0 iff every cell passed) so tests/test_crash_grid.py can wire
the full grid into tier-1.

Usage:
    python tools/crash_grid.py [--json [PATH|-]] [--parallel N]
    python tools/crash_grid.py --child <artifact> <path> <payload>
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Padded so a half-flushed tmp (the tmp-partial window) is torn JSON,
# never a prefix that happens to parse.
_PAD = "x" * 256


def _payload(v: str) -> bytes:
    return json.dumps({"v": v, "pad": _PAD}).encode()


def _decode(raw: bytes) -> Optional[str]:
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    return doc.get("v") if isinstance(doc, dict) else None


def _validate(raw: bytes) -> bool:
    return _decode(raw) in ("A", "B")


def child_main(name: str, path: str, payload: str) -> int:
    """One write of `payload` under artifact `name` — the process the
    parent kills at a declared edge (or lets finish, as the control)."""
    from spacedrive_tpu import persist

    # The grid driver is THE sanctioned dynamic consumer: it
    # iterates the registry itself, so the static-name rule is
    # what it exists to exercise, not to obey.
    # sdlint: ok[io-durability]
    persist.atomic_write(name, path, _payload(payload))
    return 0


def _expected(kind: str, edge: str) -> Tuple[str, ...]:
    """Which payloads may legally survive a kill at `edge` + recovery."""
    if edge in ("tmp-open", "tmp-partial"):
        return ("A",)                   # torn tmp discarded, A committed
    if edge == "renamed":
        return ("B",)                   # rename happened before the kill
    # complete tmp (tmp-full / fsync-file): WAL promotes, atomic discards
    return ("B",) if kind == "wal" else ("A",)


def _spawn(name: str, path: str, payload: str,
           crashpoint: Optional[str]) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("SDTPU_PERSIST_CRASHPOINT", None)
    if crashpoint:
        env["SDTPU_PERSIST_CRASHPOINT"] = crashpoint
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--child", name, path, payload],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=60)


def run_round(name: str, kind: str, edge: Optional[str],
              round_dir: str) -> Dict:
    """One grid cell: seed A, kill a child writing B at `edge` (or run
    the control to completion), recover, judge the survivor."""
    from spacedrive_tpu import persist

    os.makedirs(round_dir)
    path = os.path.join(round_dir, "artifact.json")
    persist.atomic_write(name, path, _payload("A"))  # committed seed

    problems: List[str] = []
    if edge is None:
        proc = _spawn(name, path, "B", None)
        if proc.returncode != 0:
            problems.append(
                f"control child exited {proc.returncode} "
                f"(stderr: {proc.stderr.strip()[-200:]})")
        want: Tuple[str, ...] = ("B",)
    else:
        proc = _spawn(name, path, "B", f"{name}:{edge}")
        if proc.returncode != -9:
            problems.append(
                f"child survived the {edge} crashpoint "
                f"(rc={proc.returncode}) — the kill seam did not fire")
        want = _expected(kind, edge)

    # sdlint: ok[io-durability]
    recovered = persist.recover(name, round_dir, validate=_validate)
    residue = [fn for fn in os.listdir(round_dir) if fn.endswith(".tmp")]
    if residue:
        problems.append(f"tmp residue survived recovery: {residue}")

    if not os.path.exists(path):
        problems.append(
            "artifact ABSENT after recovery — the committed seed was "
            "lost (rename tore the old copy away without the new)")
        found = None
    else:
        with open(path, "rb") as f:
            found = _decode(f.read())
        if found not in ("A", "B"):
            problems.append(
                f"artifact TORN after recovery (payload {found!r})")
        elif found not in want:
            problems.append(
                f"expected {'/'.join(want)} after kill at {edge}, "
                f"found {found}")
    return {
        "artifact": name, "kind": kind,
        "edge": edge or "control", "found": found,
        "recovered": recovered, "problems": problems,
    }


def build_grid() -> List[Tuple[str, str, Optional[str]]]:
    from spacedrive_tpu import persist

    cells: List[Tuple[str, str, Optional[str]]] = []
    for name in sorted(persist.ARTIFACTS):
        edges = persist.edges_for(name)  # sdlint: ok[io-durability]
        if not edges:
            continue  # append (SQLite WAL owns it) / scratch (removed)
        kind = persist.ARTIFACTS[name].kind
        for edge in edges:
            cells.append((name, kind, edge))
        cells.append((name, kind, None))  # control
    return cells


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/crash_grid.py",
        description="kill -9 the persist seam at every declared "
                    "durability edge; assert valid-or-absent recovery")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH",
                    help="emit the grid as a JSON artifact "
                         "(default '-': stdout)")
    ap.add_argument("--parallel", type=int, default=8,
                    help="concurrent kill children (default 8)")
    ap.add_argument("--child", nargs=3,
                    metavar=("ARTIFACT", "PATH", "PAYLOAD"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return child_main(*args.child)

    from spacedrive_tpu import persist

    cells = build_grid()
    rounds: List[Dict] = []
    with persist.scratch("bench.workdir") as root:
        with ThreadPoolExecutor(max_workers=max(1, args.parallel)) as ex:
            futs = [
                ex.submit(run_round, name, kind, edge,
                          os.path.join(root, f"r{i:03d}"))
                for i, (name, kind, edge) in enumerate(cells)]
            rounds = [f.result() for f in futs]

    failures = [
        f"{r['artifact']}@{r['edge']}: {p}"
        for r in rounds for p in r["problems"]]
    doc = {
        "metric": "crash_grid",
        "artifacts": sorted({r["artifact"] for r in rounds}),
        "cells": len(rounds),
        "kills": sum(1 for r in rounds if r["edge"] != "control"),
        "failures": failures,
        "pass": not failures,
        "rounds": rounds,
    }
    if args.json == "-":
        print(json.dumps(doc, indent=1))
    elif args.json:
        persist.atomic_write("bench.artifact", args.json,
                             json.dumps(doc, indent=1))
    summary = (f"crash_grid: {doc['cells']} cells "
               f"({doc['kills']} kills) over "
               f"{len(doc['artifacts'])} artifacts — "
               + ("PASS" if doc["pass"] else
                  f"{len(failures)} FAILURE(S)"))
    print(summary, file=sys.stderr)
    for f in failures:
        print(f"  {f}", file=sys.stderr)
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
