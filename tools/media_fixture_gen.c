/* Fixture generator: tiny real-codec video files for the media tests.
 *
 * Encodes a deterministic animated pattern with the SYSTEM ffmpeg
 * libraries (libavcodec 59 + libx264/libx265/libvpx/libaom — present in
 * this image as shared libs + dev headers) into the codecs the runtime
 * decode path must handle: CABAC Main/High-profile H.264, HEVC, VP9,
 * AV1. The outputs are committed as tests/fixtures/video/* and decoded
 * in tests by the cv2-backed runtime path (media/video.py) — mirroring
 * the reference, whose thumbnailer handles any codec by linking ffmpeg
 * (/root/reference/crates/ffmpeg/src/movie_decoder.rs:32).
 *
 * Build:  gcc -O2 -o media_fixture_gen tools/media_fixture_gen.c \
 *             -lavformat -lavcodec -lavutil
 * Run:    ./media_fixture_gen <outdir>
 *
 * This tool runs at FIXTURE GENERATION time only — the runtime imports
 * nothing from here; committed fixtures keep the suite hermetic.
 */
#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/imgutils.h>
#include <libavutil/opt.h>
#include <stdio.h>
#include <string.h>

#define W 128
#define H 96
#define FPS 10
#define NFRAMES 25

/* Deterministic pattern: diagonal gradient + a moving bright box so
 * every frame differs and a mid-stream frame is visually distinct. */
static void fill_frame(AVFrame *f, int t) {
  for (int y = 0; y < H; y++)
    for (int x = 0; x < W; x++)
      f->data[0][y * f->linesize[0] + x] = (uint8_t)((x * 2 + y + t * 7) & 0xFF);
  int bx = (t * 9) % (W - 32), by = (t * 5) % (H - 24);
  for (int y = by; y < by + 24; y++)
    for (int x = bx; x < bx + 32; x++)
      f->data[0][y * f->linesize[0] + x] = 235;
  for (int y = 0; y < H / 2; y++)
    for (int x = 0; x < W / 2; x++) {
      f->data[1][y * f->linesize[1] + x] = (uint8_t)((x * 4 + t * 3) & 0xFF);
      f->data[2][y * f->linesize[2] + x] = (uint8_t)((y * 4 + 255 - t * 3) & 0xFF);
    }
}

static int encode_file(const char *path, const char *enc_name,
                       const char *profile, int crf) {
  AVFormatContext *oc = NULL;
  int ret = avformat_alloc_output_context2(&oc, NULL, NULL, path);
  if (ret < 0 || !oc) { fprintf(stderr, "mux alloc %s\n", path); return -1; }

  const AVCodec *codec = avcodec_find_encoder_by_name(enc_name);
  if (!codec) { fprintf(stderr, "no encoder %s\n", enc_name); return -1; }
  AVStream *st = avformat_new_stream(oc, NULL);
  AVCodecContext *c = avcodec_alloc_context3(codec);
  c->width = W;
  c->height = H;
  c->pix_fmt = AV_PIX_FMT_YUV420P;
  c->time_base = (AVRational){1, FPS};
  c->gop_size = 8; /* several keyframes so 10%-seek lands near one */
  if (oc->oformat->flags & AVFMT_GLOBALHEADER)
    c->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
  if (profile) av_opt_set(c->priv_data, "profile", profile, 0);
  if (crf >= 0) av_opt_set_int(c->priv_data, "crf", crf, 0);
  if (!strcmp(enc_name, "libx264")) {
    /* CABAC is the point of this fixture: Main/High default to it, but
     * pin it explicitly so a build quirk can't hand back CAVLC. */
    av_opt_set(c->priv_data, "x264-params", "cabac=1", 0);
  }
  if (!strcmp(enc_name, "libaom-av1")) {
    av_opt_set_int(c->priv_data, "cpu-used", 8, 0); /* keep encode fast */
    c->bit_rate = 200000;
  }
  if (!strcmp(enc_name, "libvpx-vp9")) c->bit_rate = 200000;
  if (!strcmp(enc_name, "mpeg2video")) c->bit_rate = 400000;

  if ((ret = avcodec_open2(c, codec, NULL)) < 0) {
    fprintf(stderr, "open %s: %d\n", enc_name, ret); return -1;
  }
  avcodec_parameters_from_context(st->codecpar, c);
  st->time_base = c->time_base;
  if (!(oc->oformat->flags & AVFMT_NOFILE) &&
      (ret = avio_open(&oc->pb, path, AVIO_FLAG_WRITE)) < 0) {
    fprintf(stderr, "avio_open %s\n", path); return -1;
  }
  if ((ret = avformat_write_header(oc, NULL)) < 0) {
    fprintf(stderr, "header %s\n", path); return -1;
  }

  AVFrame *frame = av_frame_alloc();
  frame->format = c->pix_fmt;
  frame->width = W;
  frame->height = H;
  av_frame_get_buffer(frame, 0);
  AVPacket *pkt = av_packet_alloc();

  for (int t = 0; t <= NFRAMES; t++) { /* t == NFRAMES: flush */
    if (t < NFRAMES) {
      av_frame_make_writable(frame);
      fill_frame(frame, t);
      frame->pts = t;
      ret = avcodec_send_frame(c, frame);
    } else {
      ret = avcodec_send_frame(c, NULL);
    }
    if (ret < 0) { fprintf(stderr, "send %d\n", t); return -1; }
    while ((ret = avcodec_receive_packet(c, pkt)) >= 0) {
      av_packet_rescale_ts(pkt, c->time_base, st->time_base);
      pkt->stream_index = st->index;
      av_interleaved_write_frame(oc, pkt);
      av_packet_unref(pkt);
    }
    if (ret != AVERROR(EAGAIN) && ret != AVERROR_EOF) {
      fprintf(stderr, "recv %d\n", ret); return -1;
    }
  }
  av_write_trailer(oc);
  avcodec_free_context(&c);
  av_frame_free(&frame);
  av_packet_free(&pkt);
  if (!(oc->oformat->flags & AVFMT_NOFILE)) avio_closep(&oc->pb);
  avformat_free_context(oc);
  printf("wrote %s (%s)\n", path, enc_name);
  return 0;
}

int main(int argc, char **argv) {
  const char *dir = argc > 1 ? argv[1] : ".";
  char path[512];
  int rc = 0;
  snprintf(path, sizeof path, "%s/cabac_main.mp4", dir);
  rc |= encode_file(path, "libx264", "main", 30);
  snprintf(path, sizeof path, "%s/cabac_high.mp4", dir);
  rc |= encode_file(path, "libx264", "high", 30);
  snprintf(path, sizeof path, "%s/hevc.mp4", dir);
  rc |= encode_file(path, "libx265", NULL, 32);
  snprintf(path, sizeof path, "%s/vp9.webm", dir);
  rc |= encode_file(path, "libvpx-vp9", NULL, -1);
  snprintf(path, sizeof path, "%s/av1.mp4", dir);
  rc |= encode_file(path, "libaom-av1", NULL, -1);
  /* .mpg has NO self-hosted parser — exercises the cv2 metadata
   * fallback in avmetadata.probe_media, not just thumbnails. */
  snprintf(path, sizeof path, "%s/mpeg2.mpg", dir);
  rc |= encode_file(path, "mpeg2video", NULL, -1);
  return rc;
}
