"""Generate the committed H.264 test fixtures (and their ground truth).

The decoder (spacedrive_tpu/media/h264.py) is validated by BYTE EQUALITY
against an independent implementation: streams produced here are decoded
at generation time with OpenCV's FFmpeg (present in this image for
decode, not encode) and the resulting planes are committed alongside the
bitstreams. A single shared-table typo cannot hide: the encoder uses the
repo's CAVLC/intra tables while FFmpeg decodes with its own — any
disagreement shows up as a generation-time mismatch.

Fixtures (under tests/fixtures/h264/):
- gradient_ipcm.mp4    I_PCM picture in a minimal MP4 (lossless image)
- mixed_cavlc.264      I_4x4 + I_16x16 + I_PCM MBs, all intra modes,
                       random small residuals, mb_qp_delta churn,
                       two slices — the CAVLC/prediction coverage stream
- mixed_cavlc.mp4      same picture muxed into MP4 (keyframe-extraction
                       path target)
- *.truth.npz          FFmpeg-decoded Y/Cb/Cr for each stream

All streams disable the in-loop deblocking filter (PPS exposes the
control flag, slices set disable_deblocking_filter_idc=1) so a deblock-
free decode is bit-exact per the spec.

Usage: python tools/h264_fixture.py [outdir]
"""

from __future__ import annotations

import os
import random
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spacedrive_tpu.media import h264 as D  # decode tables reused to encode


class BitWriter:
    def __init__(self):
        self.bits: list = []

    def u(self, val: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.bits.append((val >> i) & 1)

    def put(self, bitstring: str) -> None:
        self.bits.extend(1 if c == "1" else 0 for c in bitstring)

    def ue(self, v: int) -> None:
        v += 1
        n = v.bit_length()
        self.bits.extend([0] * (n - 1))
        self.u(v, n)

    def se(self, v: int) -> None:
        self.ue(2 * v - 1 if v > 0 else -2 * v)

    def align_zero(self) -> None:
        while len(self.bits) % 8:
            self.bits.append(0)

    def stop(self) -> None:  # rbsp_trailing_bits
        self.bits.append(1)
        while len(self.bits) % 8:
            self.bits.append(0)

    def bytes(self) -> bytes:
        out = bytearray()
        for i in range(0, len(self.bits), 8):
            b = 0
            for bit in self.bits[i:i + 8]:
                b = (b << 1) | bit
            out.append(b)
        return bytes(out)


def to_nal(rbsp: bytes, nal_type: int, ref_idc: int = 3) -> bytes:
    out = bytearray([(ref_idc << 5) | nal_type])
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 3:
            out.append(3)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)


# -- encode-side VLC tables: invert the decoder's ---------------------------

def _inv(table):
    return {v: k for k, v in table.items()}

_ENC_CT = {0: _inv(D._COEFF_TOKEN_0), 2: _inv(D._COEFF_TOKEN_2),
           4: _inv(D._COEFF_TOKEN_4), -1: _inv(D._COEFF_TOKEN_CHROMA_DC)}
_ENC_TZ = {k: _inv(v) for k, v in D._TOTAL_ZEROS_4x4.items()}
_ENC_TZC = {k: _inv(v) for k, v in D._TOTAL_ZEROS_CHROMA_DC.items()}
_ENC_RB = {k: _inv(v) for k, v in D._RUN_BEFORE.items()}


def encode_residual(w: BitWriter, coeffs, nC: int, max_coeffs: int) -> None:
    """CAVLC-encode one block of scan-ordered levels (§9.2 inverse).
    Levels must stay small enough to avoid the level_prefix escape
    (|level| <= 7 is always safe at any suffix length)."""
    nz = [(i, c) for i, c in enumerate(coeffs[:max_coeffs]) if c]
    total = len(nz)
    # trailing ones: |1| levels at the highest scan positions (max 3)
    t1 = 0
    for i in range(total - 1, -1, -1):
        if abs(nz[i][1]) == 1 and t1 < 3:
            t1 += 1
        else:
            break
    # coeff_token
    if nC == -1:
        w.put(_ENC_CT[-1][(total, t1)])
    elif nC < 2:
        w.put(_ENC_CT[0][(total, t1)])
    elif nC < 4:
        w.put(_ENC_CT[2][(total, t1)])
    elif nC < 8:
        w.put(_ENC_CT[4][(total, t1)])
    else:
        w.u(3 if total == 0 else ((total - 1) << 2) | t1, 6)
    if total == 0:
        return
    coded = nz[::-1]  # highest frequency first
    for i in range(t1):
        w.u(1 if coded[i][1] < 0 else 0, 1)
    suffix_len = 1 if (total > 10 and t1 < 3) else 0
    for i in range(t1, total):
        level = coded[i][1]
        code = 2 * level - 2 if level > 0 else -2 * level - 1
        if i == t1 and t1 < 3:
            code -= 2
        if suffix_len == 0:
            if code < 14:
                w.u(1, code + 1)  # prefix zeros then 1
                # (prefix == code, no suffix)
                pass
            else:
                assert code < 30, "level escape not supported by encoder"
                w.u(1, 15)  # prefix 14
                w.u(code - 14, 4)
        else:
            prefix = code >> suffix_len
            assert prefix < 15, "level escape not supported by encoder"
            w.u(1, prefix + 1)
            w.u(code & ((1 << suffix_len) - 1), suffix_len)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1
    # total_zeros
    highest = coded[0][0]
    total_zeros = highest + 1 - total
    if total < max_coeffs:
        if nC == -1:
            w.put(_ENC_TZC[total][total_zeros])
        else:
            w.put(_ENC_TZ[total][total_zeros])
    # run_before per coded level except the last
    zeros_left = total_zeros
    positions = [p for p, _ in coded]
    for i in range(total - 1):
        run = positions[i] - positions[i + 1] - 1
        if zeros_left > 0:
            w.put(_ENC_RB[min(zeros_left, 7)][run])
        else:
            assert run == 0
        zeros_left -= run


# -- parameter sets ---------------------------------------------------------

def make_sps(w_mbs: int, h_mbs: int) -> bytes:
    w = BitWriter()
    w.u(66, 8)       # baseline
    w.u(0xC0, 8)
    w.u(20, 8)       # level 2.0
    w.ue(0)          # sps_id
    w.ue(0)          # log2_max_frame_num_minus4
    w.ue(2)          # pic_order_cnt_type
    w.ue(0)          # max_num_ref_frames
    w.u(0, 1)
    w.ue(w_mbs - 1)
    w.ue(h_mbs - 1)
    w.u(1, 1)        # frame_mbs_only
    w.u(0, 1)
    w.u(0, 1)        # no cropping
    w.u(0, 1)        # no vui
    w.stop()
    return to_nal(w.bytes(), 7)


def make_pps(qp: int) -> bytes:
    w = BitWriter()
    w.ue(0)
    w.ue(0)
    w.u(0, 1)        # CAVLC
    w.u(0, 1)
    w.ue(0)
    w.ue(0)
    w.ue(0)
    w.u(0, 1)
    w.u(0, 2)
    w.se(qp - 26)    # pic_init_qp
    w.se(0)
    w.se(0)          # chroma_qp_index_offset
    w.u(1, 1)        # deblocking_filter_control_present
    w.u(0, 1)
    w.u(0, 1)
    w.stop()
    return to_nal(w.bytes(), 8)


def slice_header(w: BitWriter, first_mb: int, qp: int, pic_init_qp: int
                 ) -> None:
    w.ue(first_mb)
    w.ue(7)          # slice_type I
    w.ue(0)          # pps_id
    w.u(0, 4)        # frame_num
    w.ue(0)          # idr_pic_id
    w.u(0, 1)        # no_output_of_prior_pics
    w.u(0, 1)        # long_term_reference
    w.se(qp - pic_init_qp)      # slice_qp_delta
    w.ue(1)          # disable_deblocking_filter_idc = 1 (OFF)


# -- I_PCM stream -----------------------------------------------------------

def ipcm_idr(y: np.ndarray, cb: np.ndarray, cr: np.ndarray, qp: int
             ) -> bytes:
    h_mb, w_mb = y.shape[0] // 16, y.shape[1] // 16
    w = BitWriter()
    slice_header(w, 0, qp, qp)
    for mby in range(h_mb):
        for mbx in range(w_mb):
            w.ue(25)
            w.align_zero()
            for r in range(16):
                for c in range(16):
                    w.u(int(y[mby * 16 + r, mbx * 16 + c]), 8)
            for plane in (cb, cr):
                for r in range(8):
                    for c in range(8):
                        w.u(int(plane[mby * 8 + r, mbx * 8 + c]), 8)
    w.stop()
    return to_nal(w.bytes(), 5)


# -- coverage stream: random modes + random residuals -----------------------

def _rand_coeffs(rng: random.Random, max_coeffs: int, density: float
                 ) -> list:
    out = [0] * max_coeffs
    for i in range(max_coeffs):
        if rng.random() < density:
            mag = rng.choice([1, 1, 1, 2, 2, 3, 4, 5])
            out[i] = mag if rng.random() < 0.5 else -mag
    return out


class _NzTracker:
    """Mirror of the decoder's nC bookkeeping, per plane."""

    def __init__(self, h_blocks: int, w_blocks: int):
        self.nz = np.full((h_blocks, w_blocks), -1, np.int16)

    def nC(self, by: int, bx: int) -> int:
        nA = int(self.nz[by, bx - 1]) if bx > 0 and \
            self.nz[by, bx - 1] >= 0 else None
        nB = int(self.nz[by - 1, bx]) if by > 0 and \
            self.nz[by - 1, bx] >= 0 else None
        if nA is not None and nB is not None:
            return (nA + nB + 1) >> 1
        return nA if nA is not None else (nB if nB is not None else 0)


def coverage_idr(w_mb: int, h_mb: int, qp0: int, seed: int,
                 slice_split: int) -> list:
    """Random-but-valid IDR picture exercising every mb_type class,
    every intra mode that availability permits, residual CAVLC at
    several QPs, as 1-2 slices. Returns slice NAL list."""
    rng = random.Random(seed)
    nzY = _NzTracker(h_mb * 4, w_mb * 4)
    nzCb = _NzTracker(h_mb * 2, w_mb * 2)
    nzCr = _NzTracker(h_mb * 2, w_mb * 2)
    i4modes = np.full((h_mb * 4, w_mb * 4), -1, np.int16)
    slice_of = np.full((h_mb, w_mb), -1, np.int32)
    nals = []
    w = BitWriter()
    qp = qp0
    sid = 0
    slice_header(w, 0, qp0, qp0)
    for addr in range(w_mb * h_mb):
        mby, mbx = divmod(addr, w_mb)
        if slice_split and addr == slice_split:
            w.stop()
            nals.append(to_nal(w.bytes(), 5))
            w = BitWriter()
            qp = qp0
            sid += 1
            slice_header(w, addr, qp0, qp0)
            # cross-slice neighbors are unavailable for nC and mode
            # prediction — fresh trackers give exactly that view
            nzY = _NzTracker(h_mb * 4, w_mb * 4)
            nzCb = _NzTracker(h_mb * 2, w_mb * 2)
            nzCr = _NzTracker(h_mb * 2, w_mb * 2)
            i4modes = np.full((h_mb * 4, w_mb * 4), -1, np.int16)
        slice_of[mby, mbx] = sid

        def _same(my, mx):
            return (0 <= my < h_mb and 0 <= mx < w_mb
                    and slice_of[my, mx] == sid)

        # neighbors in a different slice are unavailable for intra
        # prediction AND nC (the decoder mirrors this; FFmpeg enforces
        # it — a cross-slice mode reference is an illegal stream)
        up = _same(mby - 1, mbx)
        left = _same(mby, mbx - 1)
        upleft = _same(mby - 1, mbx - 1)
        upright = _same(mby - 1, mbx + 1)
        kind = rng.choice(["i4", "i4", "i16", "i16", "pcm"])
        if kind == "pcm":
            w.ue(25)
            w.align_zero()
            for _ in range(256 + 128):
                w.u(rng.randrange(256), 8)
            nzY.nz[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 16
            nzCb.nz[mby * 2:mby * 2 + 2, mbx * 2:mbx * 2 + 2] = 16
            nzCr.nz[mby * 2:mby * 2 + 2, mbx * 2:mbx * 2 + 2] = 16
            i4modes[mby * 4:mby * 4 + 4, mbx * 4:mbx * 4 + 4] = 2
            continue
        if kind == "i16":
            pred = rng.choice([m for m, need in
                               ((0, up), (1, left), (2, True),
                                (3, up and left and upleft)) if need])
            cbp_chroma = rng.choice([0, 1, 2])
            cbp_luma = rng.choice([0, 15])
            mb_type = 1 + pred + 4 * (cbp_chroma + 3 * (cbp_luma == 15))
            w.ue(mb_type)
            chroma_mode = rng.choice(
                [m for m, need in ((0, True), (1, left), (2, up),
                                   (3, up and left and upleft)) if need])
            w.ue(chroma_mode)
            dqp = rng.choice([-2, -1, 0, 0, 0, 1, 2])
            if not (26 <= qp + dqp <= 44):
                dqp = 0
            qp += dqp
            w.se(dqp)
            # luma DC
            nc = nzY.nC(mby * 4, mbx * 4)
            dc = _rand_coeffs(rng, 16, 0.3)
            encode_residual(w, dc, nc, 16)
            for k in range(16):
                br, bc = D._BLK4_ORDER[k]
                gy, gx = mby * 4 + br, mbx * 4 + bc
                if cbp_luma:
                    nc = nzY.nC(gy, gx)
                    ac = _rand_coeffs(rng, 15, 0.25)
                    encode_residual(w, ac, nc, 15)
                    nzY.nz[gy, gx] = sum(1 for c in ac if c)
                else:
                    nzY.nz[gy, gx] = 0
                i4modes[gy, gx] = 2
        else:  # I_4x4
            w.ue(0)
            modes = []
            for k in range(16):
                br, bc = D._BLK4_ORDER[k]
                gy, gx = mby * 4 + br, mbx * 4 + bc
                lm = i4modes[gy, gx - 1] if gx > 0 else -1
                tm = i4modes[gy - 1, gx] if gy > 0 else -1
                predm = 2 if lm < 0 or tm < 0 else min(int(lm), int(tm))
                # availability for this block (same rules as the decoder)
                t_ok = (br > 0) or up
                l_ok = (bc > 0) or left
                tl_ok = (br > 0 and bc > 0) or (br > 0 and left) or \
                    (bc > 0 and up) or upleft
                allowed = [2]
                if t_ok:
                    allowed += [0, 3, 7]
                if l_ok:
                    allowed += [1, 8]
                if t_ok and l_ok and tl_ok:
                    allowed += [4, 5, 6]
                mode = rng.choice(allowed)
                i4modes[gy, gx] = mode
                modes.append(mode)
                if mode == predm:
                    w.u(1, 1)
                else:
                    w.u(0, 1)
                    w.u(mode if mode < predm else mode - 1, 3)
            chroma_mode = rng.choice(
                [m for m, need in ((0, True), (1, left), (2, up),
                                   (3, up and left and upleft)) if need])
            w.ue(chroma_mode)
            cbp_luma = rng.choice([0, 3, 15, 9, 6])
            cbp_chroma = rng.choice([0, 1, 2])
            cbp = cbp_luma | (cbp_chroma << 4)
            w.ue(D._CBP_INTRA.index(cbp))
            if cbp:
                dqp = rng.choice([-1, 0, 0, 1])
                if not (26 <= qp + dqp <= 44):
                    dqp = 0
                qp += dqp
                w.se(dqp)
            for k in range(16):
                br, bc = D._BLK4_ORDER[k]
                gy, gx = mby * 4 + br, mbx * 4 + bc
                blk8 = (br // 2) * 2 + (bc // 2)
                if cbp_luma & (1 << blk8):
                    nc = nzY.nC(gy, gx)
                    co = _rand_coeffs(rng, 16, 0.25)
                    encode_residual(w, co, nc, 16)
                    nzY.nz[gy, gx] = sum(1 for c in co if c)
                else:
                    nzY.nz[gy, gx] = 0
        # chroma residual (shared by i4/i16)
        dcs = []
        for _plane in range(2):
            if cbp_chroma:
                dc = _rand_coeffs(rng, 4, 0.4)
                encode_residual(w, dc, -1, 4)
            dcs.append(None)
        for tracker in (nzCb, nzCr):
            for br in range(2):
                for bc in range(2):
                    gy, gx = mby * 2 + br, mbx * 2 + bc
                    if cbp_chroma == 2:
                        nc = tracker.nC(gy, gx)
                        ac = _rand_coeffs(rng, 15, 0.2)
                        encode_residual(w, ac, nc, 15)
                        tracker.nz[gy, gx] = sum(1 for c in ac if c)
                    else:
                        tracker.nz[gy, gx] = 0
    w.stop()
    nals.append(to_nal(w.bytes(), 5))
    return nals


# -- minimal MP4 muxer ------------------------------------------------------

def _box(typ: bytes, payload: bytes) -> bytes:
    return struct.pack(">I4s", 8 + len(payload), typ) + payload


def _full(typ: bytes, version: int, flags: int, payload: bytes) -> bytes:
    return _box(typ, struct.pack(">B3s", version,
                                 flags.to_bytes(3, "big")) + payload)


def mux_mp4(sps_nal: bytes, pps_nal: bytes, slice_nals: list,
            width: int, height: int) -> bytes:
    """One-keyframe MP4: ftyp + mdat(sample) + moov with a full sample
    table (ISO/IEC 14496-12 + -15 avcC)."""
    sample = b"".join(struct.pack(">I", len(n)) + n for n in slice_nals)
    ftyp = _box(b"ftyp", b"isom\x00\x00\x02\x00isomavc1")
    mdat = _box(b"mdat", sample)
    sample_off = len(ftyp) + 8  # into mdat payload

    avcc = (b"\x01" + sps_nal[1:4] + b"\xff" +
            b"\xe1" + struct.pack(">H", len(sps_nal)) + sps_nal +
            b"\x01" + struct.pack(">H", len(pps_nal)) + pps_nal)
    avc1 = _box(b"avc1",
                b"\x00" * 6 + struct.pack(">H", 1) +      # dref index
                b"\x00" * 16 +
                struct.pack(">HH", width, height) +
                struct.pack(">II", 0x480000, 0x480000) +  # dpi
                b"\x00" * 4 +
                struct.pack(">H", 1) +                    # frame count
                b"\x00" * 32 +
                struct.pack(">H", 0x18) +
                struct.pack(">h", -1) +
                _box(b"avcC", avcc))
    stsd = _full(b"stsd", 0, 0, struct.pack(">I", 1) + avc1)
    stts = _full(b"stts", 0, 0, struct.pack(">III", 1, 1, 1000))
    stsc = _full(b"stsc", 0, 0, struct.pack(">IIII", 1, 1, 1, 1))
    stsz = _full(b"stsz", 0, 0, struct.pack(">III", 0, 1, len(sample)))
    stco = _full(b"stco", 0, 0, struct.pack(">II", 1, sample_off))
    stss = _full(b"stss", 0, 0, struct.pack(">II", 1, 1))
    stbl = _box(b"stbl", stsd + stts + stsc + stsz + stco + stss)
    url_ = _full(b"url ", 0, 1, b"")
    dref = _full(b"dref", 0, 0, struct.pack(">I", 1) + url_)
    dinf = _box(b"dinf", dref)
    vmhd = _full(b"vmhd", 0, 1, b"\x00" * 8)
    minf = _box(b"minf", vmhd + dinf + stbl)
    hdlr = _full(b"hdlr", 0, 0, b"\x00" * 4 + b"vide" + b"\x00" * 12 +
                 b"sdtpu\x00")
    mdhd = _full(b"mdhd", 0, 0, struct.pack(">IIIIHH", 0, 0, 1000, 1000,
                                            0x55C4, 0))
    mdia = _box(b"mdia", mdhd + hdlr + minf)
    mat = (struct.pack(">iii", 0x10000, 0, 0) +
           struct.pack(">iii", 0, 0x10000, 0) +
           struct.pack(">iii", 0, 0, 0x40000000))
    tkhd = _full(b"tkhd", 0, 7,
                 struct.pack(">IIII", 0, 0, 1, 0) +
                 struct.pack(">I", 1000) + b"\x00" * 8 +
                 struct.pack(">hhhh", 0, 0, 0, 0) + mat +
                 struct.pack(">II", width << 16, height << 16))
    trak = _box(b"trak", tkhd + mdia)
    mvhd = _full(b"mvhd", 0, 0,
                 struct.pack(">IIII", 0, 0, 1000, 1000) +
                 struct.pack(">I", 0x00010000) + struct.pack(">H", 0x0100) +
                 b"\x00" * 10 + mat + b"\x00" * 24 +
                 struct.pack(">I", 2))
    moov = _box(b"moov", mvhd + trak)
    return ftyp + mdat + moov


# -- minimal MPEG-TS muxer --------------------------------------------------

def _crc32_mpeg(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b << 24
        for _ in range(8):
            crc = ((crc << 1) ^ 0x04C11DB7) & 0xFFFFFFFF if crc & 0x80000000 \
                else (crc << 1) & 0xFFFFFFFF
    return crc


def _ts_packet(pid: int, payload: bytes, pusi: bool, cc: int) -> bytes:
    """One 188-byte packet; short payloads padded via adaptation field."""
    header = bytes([
        0x47,
        (0x40 if pusi else 0) | ((pid >> 8) & 0x1F),
        pid & 0xFF,
        0,  # afc+cc filled below
    ])
    room = 184
    if len(payload) < room:
        stuff = room - len(payload) - 1  # 1 byte af length
        af = bytes([max(stuff, 0)]) + (b"\x00" + b"\xff" * (stuff - 1)
                                       if stuff > 0 else b"")
        body = af + payload
        afc = 3
    else:
        body = payload[:184]
        afc = 1
    pkt = bytearray(header + body)
    pkt[3] = (afc << 4) | (cc & 0x0F)
    return bytes(pkt)


def _psi_section(table_id: int, body: bytes, tsid: int = 1) -> bytes:
    sec = bytes([table_id]) + struct.pack(
        ">H", 0xB000 | (len(body) + 9)) + struct.pack(">H", tsid) + \
        bytes([0xC1, 0x00, 0x00]) + body
    return b"\x00" + sec + struct.pack(">I", _crc32_mpeg(sec))


def mux_ts(slice_nals: list, sps_nal: bytes, pps_nal: bytes,
           m2ts: bool = False, repeats: int = 3) -> bytes:
    """H.264 Annex-B access unit(s) in a transport stream: PAT → PMT
    (stream_type 0x1B on PID 0x100) → PES packets. `repeats` emits the
    picture several times so a mid-file seek still finds an IDR."""
    pmt_pid, vpid = 0x1000, 0x100
    pat = _psi_section(0x00, struct.pack(">HH", 1, 0xE000 | pmt_pid))
    pmt = _psi_section(0x02, struct.pack(">H", 0xE000 | vpid) +
                       struct.pack(">H", 0xF000) +
                       bytes([0x1B]) +
                       struct.pack(">H", 0xE000 | vpid) +
                       struct.pack(">H", 0xF000), tsid=1)
    au = b"".join(b"\x00\x00\x00\x01" + n
                  for n in [sps_nal, pps_nal] + slice_nals)
    pes = (b"\x00\x00\x01\xe0" + struct.pack(">H", 0) +
           bytes([0x80, 0x00, 0x00]) + au)

    out = bytearray()
    cc = {0: 0, pmt_pid: 0, vpid: 0}

    def emit(pid, payload, pusi):
        out.extend(_ts_packet(pid, payload, pusi, cc[pid]))
        cc[pid] = (cc[pid] + 1) & 0x0F

    for _ in range(repeats):
        emit(0, pat, True)
        emit(pmt_pid, pmt, True)
        pos = 0
        first = True
        while pos < len(pes):
            chunk = pes[pos:pos + 184]
            emit(vpid, chunk, first)
            first = False
            pos += 184
    data = bytes(out)
    if m2ts:
        data = b"".join(b"\x00\x00\x00\x00" + data[i:i + 188]
                        for i in range(0, len(data), 188))
    return data


# -- ground truth via OpenCV/FFmpeg -----------------------------------------

def ffmpeg_truth(annexb: bytes, tmpdir: str, name: str):
    import cv2
    p = os.path.join(tmpdir, name + ".264")
    with open(p, "wb") as f:
        f.write(annexb)
    cap = cv2.VideoCapture(p)
    cap.set(cv2.CAP_PROP_CONVERT_RGB, 0)
    ok, ypl = cap.read()
    if not ok:
        raise RuntimeError(f"FFmpeg refused {name}")
    cap.release()
    # second pass for chroma via BGR (lossy conversion — used only as a
    # sanity bound, Y is the exact plane)
    cap = cv2.VideoCapture(p)
    ok, bgr = cap.read()
    cap.release()
    return ypl, bgr


def main(outdir: str) -> None:
    os.makedirs(outdir, exist_ok=True)
    import cv2

    # ---- fixture 1: I_PCM gradient in MP4 -------------------------------
    H, W = 48, 80
    yy, xx = np.mgrid[0:H, 0:W]
    y = ((xx * 3 + yy * 2) % 240 + 8).astype(np.uint8)
    cb = (np.linspace(60, 180, (H // 2) * (W // 2)) % 255).astype(
        np.uint8).reshape(H // 2, W // 2)
    cr = (np.linspace(180, 60, (H // 2) * (W // 2)) % 255).astype(
        np.uint8).reshape(H // 2, W // 2)
    sps, pps = make_sps(W // 16, H // 16), make_pps(30)
    idr = ipcm_idr(y, cb, cr, 30)
    annexb = b"".join(b"\x00\x00\x00\x01" + n for n in (sps, pps, idr))
    ypl, _ = ffmpeg_truth(annexb, outdir, "gradient_ipcm")
    assert np.array_equal(ypl, y), "I_PCM luma must round-trip exactly"
    mp4 = mux_mp4(sps, pps, [idr], W, H)
    with open(os.path.join(outdir, "gradient_ipcm.mp4"), "wb") as f:
        f.write(mp4)
    # cv2 must also read the MP4 container itself
    capm = cv2.VideoCapture(os.path.join(outdir, "gradient_ipcm.mp4"))
    okm, _ = capm.read()
    capm.release()
    assert okm, "muxed MP4 unreadable by FFmpeg"
    np.savez_compressed(os.path.join(outdir, "gradient_ipcm.truth.npz"),
                        Y=y, Cb=cb, Cr=cr)
    print("gradient_ipcm: ok (Y exact vs FFmpeg, MP4 readable)")

    # ---- fixture 2: CAVLC/intra coverage --------------------------------
    W2, H2 = 96, 64  # 6x4 MBs
    sps2, pps2 = make_sps(W2 // 16, H2 // 16), make_pps(32)
    nals = coverage_idr(W2 // 16, H2 // 16, 32, seed=1234, slice_split=13)
    annexb2 = b"".join(b"\x00\x00\x00\x01" + n
                       for n in [sps2, pps2] + nals)
    ypl2, bgr2 = ffmpeg_truth(annexb2, outdir, "mixed_cavlc")
    with open(os.path.join(outdir, "mixed_cavlc.264"), "wb") as f:
        f.write(annexb2)
    mp42 = mux_mp4(sps2, pps2, nals, W2, H2)
    with open(os.path.join(outdir, "mixed_cavlc.mp4"), "wb") as f:
        f.write(mp42)
    np.savez_compressed(os.path.join(outdir, "mixed_cavlc.truth.npz"),
                        Y=ypl2, BGR=bgr2)
    print("mixed_cavlc: FFmpeg decoded", ypl2.shape,
          "slices:", len(nals))

    # ---- fixture 3: the same pictures in transport streams --------------
    ts = mux_ts([idr], sps, pps)
    with open(os.path.join(outdir, "gradient_ipcm.ts"), "wb") as f:
        f.write(ts)
    m2ts = mux_ts(nals, sps2, pps2, m2ts=True)
    with open(os.path.join(outdir, "mixed_cavlc.m2ts"), "wb") as f:
        f.write(m2ts)
    for name in ("gradient_ipcm.ts", "mixed_cavlc.m2ts"):
        cap = cv2.VideoCapture(os.path.join(outdir, name))
        okt, _f = cap.read()
        cap.release()
        assert okt, f"FFmpeg refused {name}"
    print("transport streams: ok (FFmpeg reads both)")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "tests/fixtures/h264")
