"""Fleet-scale load harness: N simulated peers against ONE real node,
with the chaos plane composed in.

ROADMAP item 3's missing proof: every robustness rail exists as a
declared registry (timeout budgets, bounded channels, admission
refusal, the supervisor tree, the health/fleet observatories), but
nothing ever drove fleet-shaped load against them — the declared
capacities were untested guesses. This harness boots a REAL Node +
ApiServer and storms it with mixed workloads over in-process stub
transports (default; no `cryptography` needed — the frames are the
same tunnel-shaped dicts the TCP plane carries):

- **pull storm**   — every peer drains the library's op log through
  the real paged `get_ops` serving path, concurrently;
- **clone burst**  — peers full-clone through the REAL windowed
  originator (`sync/clone_serve.serve_clone_stream`: CLONE_WINDOW in
  flight, watermark acks, the fair-share page-fetch gate) into the
  real receiver (`sync/ingest.pump_clone_stream`), surviving injected
  mid-clone disconnects by reconnecting from the durable watermark;
- **API fan-in**   — HTTP clients hammer rspc routes against the
  narrowed `api.http.inflight` admission window (503 SHED is the
  measured shed-load edge);
- **ws flood**     — real websocket subscribers (some wedged by the
  `api.ws.send` chaos fault) under an EventBus notification flood:
  the per-subscription channels must shed, never wedge the node;
- **ingest storm** — peers push remote ops INTO the node
  (`receive_crdt_operations` + the `sync.ingest.apply` and
  `store.commit` faults: injected sqlite BUSY must degrade to
  latency through the declared `store.busy` backoff);
- **spacedrop**    — offers over real tunnels when `cryptography` is
  available (skipped, and recorded as skipped, in stub containers).

`--chaos` arms a chaos.py spec for the whole run (seeded via
`--seed`, so a failing storm replays); `--json` emits a BENCH-style
artifact (per-workload throughput/latency percentiles, the
chaos/backoff/timeout/shed counters, health observatory samples with
saturation attribution, and the incident observatory's bundle
headers + per-fingerprint dedup counts — the storm's own postmortem
record); `--gate` exits non-zero on:

- any sanitizer/race/chan-overflow violation,
- a WEDGE: any coalesce channel still full at quiescence (a consumer
  the run permanently stuck),
- STARVATION: the slowest clone peer's apply rate below
  ``--fairness-floor`` x the mean (the fair-share gate's contract),
- UNATTRIBUTED SATURATION: a health sample whose non-ok subsystem
  carries no attribution naming a declared resource,
- UNATTRIBUTED INCIDENT: a frozen bundle whose trigger names no
  declared resource (bundles under chaos are expected; causeless
  ones mean the capture path lost the attribution).

    python -m tools.load_bench --json - --gate
    python -m tools.load_bench --peers 128 --chaos \\
        'sync.clone.page=disconnect:0.05;store.commit=error:0.1'
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time
import uuid as uuidlib
from typing import Any, Dict, List, Optional

try:
    # Seed the objects package: in runtimes without `cryptography` the
    # first attempt fails but leaves the non-crypto submodules cached,
    # after which mount_router imports cleanly (container quirk; no-op
    # where the dependency exists).
    import spacedrive_tpu.objects  # noqa: F401
except ModuleNotFoundError:
    pass

from spacedrive_tpu import channels, chaos, flags, sanitize, telemetry
from spacedrive_tpu.p2p import wire

DEFAULT_CHAOS = (
    "sync.clone.page=disconnect:0.04;"
    "sync.ingest.apply=error:0.03,delay:5ms:0.2;"
    "api.http.dispatch=delay:10ms:0.5;"
    "api.ws.send=wedge:0.03;"
    "store.commit=error:0.1")

_WIRE_CLOSED = "__wire_closed__"


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _lat_ms(samples: List[float]) -> Dict[str, float]:
    s = sorted(samples)
    return {"p50": round(_pct(s, 0.50) * 1e3, 3),
            "p95": round(_pct(s, 0.95) * 1e3, 3),
            "p99": round(_pct(s, 0.99) * 1e3, 3),
            "n": len(s)}


# -- stub transport ----------------------------------------------------------

class _StubEnd:
    """One end of an in-process duplex wire, tunnel-shaped
    (send/send_nowait/drain/recv/close) so the REAL clone originator
    and receiver speak through it unchanged. Frames ride declared
    bench.load.wire registry channels — the stub transport is itself
    depth-disciplined."""

    def __init__(self, out: channels.Channel, inbox: channels.Channel):
        self.out = out
        self.inbox = inbox

    async def send(self, msg: Any) -> None:
        # Same audit seam as the TCP tunnel (nbytes unknown on the
        # loopback wire — size caps are the transport's to enforce):
        # the stub fleet storms the REAL frame contracts too.
        wire.audit_frame(msg, "out")
        await self.out.put(msg)

    def send_nowait(self, msg: Any) -> None:  # sdlint: ok[queue-discipline] the buffer IS the declared bench.load.wire channel
        wire.audit_frame(msg, "out")
        self.out.put_nowait(msg)

    async def drain(self) -> None:
        await asyncio.sleep(0)

    async def recv(self) -> Any:
        frame = await self.inbox.get()
        if frame == _WIRE_CLOSED:
            raise ConnectionError("stub wire: peer end closed")
        wire.audit_frame(frame, "in")
        return frame

    def close(self) -> None:
        # Best-effort close signal (a torn TCP conn, in stub form):
        # skipped when the pipe is momentarily full — the harness
        # additionally bounds every stream attempt with its own
        # wall-clock timeout, so a lost close can only cost that.
        if len(self.out) < self.out.capacity:
            self.out.put_nowait(_WIRE_CLOSED)


def _stub_wire():
    a2b = channels.channel("bench.load.wire")
    b2a = channels.channel("bench.load.wire")
    return _StubEnd(a2b, b2a), _StubEnd(b2a, a2b)


# -- simulated peers ---------------------------------------------------------

def _mk_peer_sync(tmp: str, name: str, origin_pub: bytes):
    """A fresh peer replica (own DB + SyncManager) registered with the
    origin instance — the stub-mode stand-in for a paired node."""
    from spacedrive_tpu.store.db import Database
    from spacedrive_tpu.sync.manager import SyncManager

    db = Database(os.path.join(tmp, f"{name}.db"))
    pub = uuidlib.uuid4().bytes
    sync = SyncManager(db, pub)
    sync.register_instance(pub)
    sync.register_instance(origin_pub)
    return sync


def _seed_library(lib, waves: int, ops_per_wave: int) -> int:
    """Solo blob waves into the node's library: the clone source and
    the pull storm's op log."""
    total = 0
    for w in range(waves):
        pubs = [uuidlib.uuid4().bytes for _ in range(ops_per_wave)]
        with lib.db.tx() as conn:  # sdlint: ok[tx-shape] one tx per wave IS one blob page — the protocol unit
            lib.sync.bulk_shared_ops(conn, "object", [
                (p, "c", None, None, {"kind": 5, "note": f"w{w}"})
                for p in pubs])
            lib.db.run_many("bench.object_insert",
                            [(p, 5, f"w{w}") for p in pubs], conn=conn)
        total += len(pubs)
    return total


# -- workloads ---------------------------------------------------------------

async def _pull_storm(lib, peers: List[Any]) -> Dict[str, Any]:
    """Every peer drains the origin's op log through the real paged
    get_ops serving path, concurrently. Injected ingest faults on the
    peer replica retry the page (the wire pull loop's re-serve, in
    miniature)."""
    from spacedrive_tpu.sync.manager import GetOpsArgs

    lat: List[float] = []
    pulled = [0] * len(peers)
    chaos_retries = [0]

    async def one(i: int, peer) -> None:
        while True:
            clocks = dict(peer.timestamps)
            clocks[peer.instance] = max(
                peer.clock.last, clocks.get(peer.instance, 0))
            t0 = time.perf_counter()
            page = await asyncio.to_thread(
                lib.sync.get_ops,
                GetOpsArgs(clocks=list(clocks.items()), count=500))
            lat.append(time.perf_counter() - t0)
            page = [op for op in page if op.instance != peer.instance]
            if not page:
                return
            for attempt in range(3):
                try:
                    n, errs = await asyncio.to_thread(
                        peer.receive_crdt_operations, page)
                    pulled[i] += n
                    break
                except chaos.ChaosError:
                    chaos_retries[0] += 1
            else:
                return

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i, p) for i, p in enumerate(peers)))
    wall = time.perf_counter() - t0
    total = sum(pulled)
    return {"peers": len(peers), "ops_pulled": total,
            "chaos_retries": chaos_retries[0],
            "wall_s": round(wall, 3),
            "ops_per_s": round(total / wall, 1) if wall else 0.0,
            "page_latency_ms": _lat_ms(lat)}


async def _clone_burst(lib, clone_peers: List[Any], attempt_s: float
                       ) -> Dict[str, Any]:
    """Full clones through the REAL windowed originator + receiver,
    one stub wire per peer, all streams sharing one fair-share
    page-fetch gate. Injected mid-clone disconnects reconnect from
    the receiver's durable watermark until the clone drains."""
    from spacedrive_tpu.sync.clone_serve import (
        serve_clone_stream,
        serve_gate,
    )
    from spacedrive_tpu.sync.ingest import pump_clone_stream

    gate = serve_gate()
    applied_ops = [0] * len(clone_peers)
    walls = [0.0] * len(clone_peers)
    reconnects = [0] * len(clone_peers)
    fast_total = [0]
    fallback_total = [0]

    async def attempt(i: int, peer) -> bool:
        """One stream attempt. True when the peer is converged (the
        originator had nothing left to stream)."""
        origin_end, peer_end = _stub_wire()
        clocks = [(k, v) for k, v in peer.timestamps.items()
                  if k != peer.instance] or [(lib.sync.instance, 0)]
        errors: List[str] = []

        async def serve() -> Any:
            try:
                served = await serve_clone_stream(
                    lib.sync, origin_end, clocks, gate=gate)
                if not served:
                    # Nothing left to stream: hand the receiver a
                    # clean end-of-stream so its pump returns (the
                    # wire caller falls through to the per-op loop
                    # here instead).
                    await origin_end.send(wire.pack("clone.done"))
                return served
            except BaseException:
                origin_end.close()  # torn conn tears both ends
                raise

        async def pump() -> int:
            # The wire pull loop consumes the stream header as its
            # page response (sync_net._pull) before handing the rest
            # to pump_clone_stream; mirror that here.
            first = await peer_end.recv()
            if not isinstance(first, dict) or \
                    first.get("kind") != "blob_stream":
                return 0  # blob_done: nothing to stream
            n, fast, fb = await pump_clone_stream(
                peer, peer_end.recv, peer_end.send, errors)
            fast_total[0] += fast
            fallback_total[0] += fb
            return n

        # return_exceptions: BOTH halves must settle before the next
        # attempt — reconnecting while the old pump's apply is still
        # in flight would read a stale watermark and re-pull pages
        # the peer already holds (a real reconnect reads the durable
        # instance row only after the old stream fully dies).
        served, applied = await asyncio.gather(
            serve(), pump(), return_exceptions=True)
        if isinstance(served, BaseException):
            raise ConnectionError(f"stream torn: {served}")
        if isinstance(applied, BaseException):
            raise ConnectionError(f"receiver torn: {applied}")
        return not served

    def _drain_tail(peer) -> int:
        """Per-op pull tail: a peer resuming after a tear is no
        longer a fresh clone target, so get_ops arbitrates the rest —
        exactly the wire protocol's fallback."""
        from spacedrive_tpu.sync.manager import GetOpsArgs

        applied = 0
        while True:
            clocks = dict(peer.timestamps)
            clocks[peer.instance] = max(
                peer.clock.last, clocks.get(peer.instance, 0))
            page = lib.sync.get_ops(GetOpsArgs(
                clocks=list(clocks.items()), count=1000))
            page = [op for op in page if op.instance != peer.instance]
            if not page:
                return applied
            for _try in range(5):
                try:
                    n, _errs = peer.receive_crdt_operations(page)  # sdlint: ok[tx-shape] per-page protocol unit
                    applied += n
                    break
                except chaos.ChaosError:
                    continue  # injected apply fault: re-offer the page
            else:
                return applied

    def _peer_log_count(peer) -> int:
        """Ground-truth ops held by the peer after convergence: a
        torn attempt's partially-counted pump return must not skew
        the fairness measurement."""
        return int(peer.db.run("bench.op_count") or 0)

    async def one(i: int, peer) -> None:
        t0 = time.perf_counter()
        while True:
            try:
                if await asyncio.wait_for(attempt(i, peer),
                                          timeout=attempt_s):
                    await asyncio.to_thread(_drain_tail, peer)
                    break
            except (ConnectionError, asyncio.TimeoutError):
                reconnects[i] += 1
                if reconnects[i] > 50:
                    raise RuntimeError(
                        f"clone peer {i}: reconnect storm never "
                        "converged")
        walls[i] = time.perf_counter() - t0
        applied_ops[i] = await asyncio.to_thread(_peer_log_count, peer)

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i, p) for i, p in enumerate(clone_peers)))
    wall = time.perf_counter() - t0
    rates = [(n / w) if w > 0 else 0.0
             for n, w in zip(applied_ops, walls)]
    mean = sum(rates) / len(rates) if rates else 0.0
    fairness = (min(rates) / mean) if mean > 0 else 1.0
    return {
        "peers": len(clone_peers),
        "wall_s": round(wall, 3),
        "ops_applied_per_peer": applied_ops,
        "peer_wall_s": [round(w, 3) for w in walls],
        "ops_per_s_per_peer": [round(r, 1) for r in rates],
        "reconnects": sum(reconnects),
        "fast_pages": fast_total[0],
        "fallback_pages": fallback_total[0],
        "fairness": {"min_rate": round(min(rates), 1) if rates else 0,
                     "mean_rate": round(mean, 1),
                     "ratio": round(fairness, 3)},
    }


async def _api_fanin(port: int, clients: int, per_client: int
                     ) -> Dict[str, Any]:
    """HTTP fan-in against the narrowed admission window: every 503
    SHED is the host refusing work instead of queueing it."""
    import aiohttp

    lat: List[float] = []
    ok = [0]
    shed = [0]
    err = [0]
    routes = ["node.health", "node.metrics", "node.spans"]

    async def one(i: int, session) -> None:
        for r in range(per_client):
            path = routes[(i + r) % len(routes)]
            t0 = time.perf_counter()
            try:
                async with session.get(
                        f"http://127.0.0.1:{port}/rspc/{path}") as resp:
                    await resp.read()
                    if resp.status == 503:
                        shed[0] += 1
                    elif resp.status == 200:
                        ok[0] += 1
                    else:
                        err[0] += 1
            except aiohttp.ClientError:
                err[0] += 1
            lat.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*(one(i, session) for i in range(clients)))
    wall = time.perf_counter() - t0
    total = ok[0] + shed[0] + err[0]
    return {"clients": clients, "requests": total, "ok": ok[0],
            "shed": shed[0], "errors": err[0],
            "wall_s": round(wall, 3),
            "req_per_s": round(total / wall, 1) if wall else 0.0,
            "latency_ms": _lat_ms(lat)}


async def _ws_flood(node, port: int, subscribers: int, events: int
                    ) -> Dict[str, Any]:
    """Real websocket subscribers under an EventBus notification
    flood. Chaos-wedged pumps must shed into
    sd_chan_shed_total{api.ws} while the node stays live."""
    import aiohttp

    received = [0] * subscribers
    stop = asyncio.Event()
    shed_before = _metric_value("sd_chan_shed_total", name="api.ws")

    async def subscriber(i: int, session) -> None:
        async with session.ws_connect(
                f"http://127.0.0.1:{port}/rspc") as ws:
            await ws.send_json({"id": 1, "type": "subscription",
                                "path": "notifications.listen"})
            while not stop.is_set():
                try:
                    msg = await ws.receive(timeout=0.25)
                except asyncio.TimeoutError:
                    continue
                if msg.type != aiohttp.WSMsgType.TEXT:
                    break
                frame = json.loads(msg.data)
                if frame.get("type") == "event":
                    received[i] += 1
            await ws.send_json({"id": 1, "type": "subscriptionStop"})

    async def flood() -> None:
        for k in range(events):
            node.events.emit({"type": "Notification",
                              "data": {"kind": "loadbench", "seq": k}})
            if k % 50 == 0:
                await asyncio.sleep(0.01)  # let pumps drain in waves
        await asyncio.sleep(0.6)  # drain window
        stop.set()

    t0 = time.perf_counter()
    async with aiohttp.ClientSession() as session:
        await asyncio.gather(flood(),
                             *(subscriber(i, session)
                               for i in range(subscribers)))
    wall = time.perf_counter() - t0
    shed = _metric_value("sd_chan_shed_total",
                         name="api.ws") - shed_before
    return {"subscribers": subscribers, "events_emitted": events,
            "delivered": sum(received),
            "delivered_per_sub": received, "shed": shed,
            "wall_s": round(wall, 3)}


async def _ingest_storm(lib, peers: List[Any], ops_per_peer: int
                        ) -> Dict[str, Any]:
    """Peers push remote ops INTO the node: the receiving replica's
    ingest + store under the sync.ingest.apply / store.commit faults.
    Injected apply errors fail a page loudly (retried — the pull
    loop's re-serve, in miniature); injected BUSY must be absorbed by
    the declared store.busy backoff and never surface at all."""
    applied = [0]
    chaos_errors = [0]
    failed_pages = [0]
    lat: List[float] = []
    busy_before = _metric_value("sd_store_busy_retries_total")
    size_hist = telemetry.REGISTRY.get("sd_store_group_size")
    size_cur = size_hist.snapshot_delta()["cursor"] \
        if size_hist is not None else None
    # Per-shard tallies: each Database (the node's library + every
    # peer replica) carries its own write actor — that IS the shard.
    shard_dbs = [("library", lib.db)] + [
        (f"peer{i}", p.db) for i, p in enumerate(peers)
        if getattr(p, "db", None) is not None]
    shards0 = {label: (d._actor.groups, d._actor.batches)
               for label, d in shard_dbs
               if getattr(d, "_actor", None) is not None}

    async def one(peer) -> None:
        ops = []
        for k in range(ops_per_peer):
            ops.extend(peer.shared_create(
                "tag", uuidlib.uuid4().bytes,
                {"name": f"storm-{k}", "color": "#101010"}))
        for start in range(0, len(ops), 32):
            page = ops[start:start + 32]
            for try_ in range(3):
                t0 = time.perf_counter()
                try:
                    n, _errs = await asyncio.to_thread(
                        lib.sync.receive_crdt_operations, page)
                    applied[0] += n
                    lat.append(time.perf_counter() - t0)
                    break
                except chaos.ChaosError:
                    chaos_errors[0] += 1
                    lat.append(time.perf_counter() - t0)
            else:
                failed_pages[0] += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(one(p) for p in peers))
    wall = time.perf_counter() - t0

    shards = {}
    for label, d in shard_dbs:
        if label not in shards0:
            continue
        g0, b0 = shards0[label]
        dg = d._actor.groups - g0
        dbatch = d._actor.batches - b0
        if dbatch:
            shards[label] = {
                "groups": dg, "batches": dbatch,
                "mean_group": round(dbatch / dg, 2) if dg else 0.0}
    group_commit: Dict[str, Any] = {
        "queue_high_water": _metric_value(
            "sd_chan_high_water", name="store.actor.queue"),
        "shards": shards,
    }
    if size_hist is not None:
        d = size_hist.snapshot_delta(size_cur)
        bounds = [f"{b:g}" for b in size_hist.buckets] + ["inf"]
        group_commit.update({
            "groups": d["count"],
            "batches_coalesced": int(d["sum"]),
            "size_histogram": {b: c for b, c in
                               zip(bounds, d["counts"]) if c},
        })

    return {"peers": len(peers),
            "ops_applied": applied[0],
            "chaos_errors": chaos_errors[0],
            "failed_pages": failed_pages[0],
            "busy_retries":
                _metric_value("sd_store_busy_retries_total")
                - busy_before,
            "wall_s": round(wall, 3),
            "ops_per_s": round(applied[0] / wall, 1) if wall else 0.0,
            "page_latency_ms": _lat_ms(lat),
            "group_commit": group_commit}


async def _write_path_ab(lib, peers: List[Any], ops_per_peer: int
                         ) -> Dict[str, Any]:
    """Before/after attribution for the write path: the same ingest
    burst once with the group-commit actor OFF (the seed's
    lock-and-pray path, SDTPU_STORE_ACTOR=0) and once ON, each leg
    with its write-lock wait total and group evidence — the artifact
    shows where the write path's time went, not just that it got
    faster. An unreported warm-up burst runs first: the chaos-fed
    commit-error backoff state it leaves behind hits both measured
    legs equally, so the comparison is order-independent."""
    lock_h = telemetry.REGISTRY.get("sd_store_write_lock_wait_seconds")
    prev = flags.raw("SDTPU_STORE_ACTOR")
    out: Dict[str, Any] = {}
    try:
        await _ingest_storm(lib, peers, max(4, ops_per_peer // 2))
        for label, setting in (("lock_path", "0"), ("actor_path", "1")):
            os.environ["SDTPU_STORE_ACTOR"] = setting
            cur = lock_h.snapshot_delta()["cursor"] \
                if lock_h is not None else None
            res = await _ingest_storm(lib, peers, ops_per_peer)
            d = lock_h.snapshot_delta(cur) if lock_h is not None else {}
            out[label] = {
                "ops_applied": res["ops_applied"],
                "ops_per_s": res["ops_per_s"],
                "page_latency_ms": res["page_latency_ms"],
                "write_lock_acquires": d.get("count", 0),
                "write_lock_wait_s": round(d.get("sum", 0.0), 4),
                "groups": res["group_commit"].get("groups", 0),
                "batches_coalesced":
                    res["group_commit"].get("batches_coalesced", 0),
            }
    finally:
        if prev is None:
            os.environ.pop("SDTPU_STORE_ACTOR", None)
        else:
            os.environ["SDTPU_STORE_ACTOR"] = prev
    return out


async def _fleet_giveup(node) -> Dict[str, Any]:
    """A dead obs peer under the real fleet poller: the HTTP
    transport's declared obs.http ladder exhausts against a refused
    port — counted into sd_backoff_gave_up_total AND frozen by the
    incident observatory as a backoff.give_up bundle — while the
    peer's row degrades to stale instead of wedging the round. Two
    monitors on purpose (a restarted observer re-polling the same
    dead peer): the second exhaustion repeats the same fingerprint
    inside the incident window, so the artifact proves dedup
    collapse, not just capture — one monitor alone won't, because the
    poller's own give-up discipline stops re-dialing a dead peer."""
    from spacedrive_tpu.fleet import FleetMonitor, HttpObsClient

    gave_before = _metric_value("sd_backoff_gave_up_total",
                                name="obs.http")
    t0 = time.perf_counter()
    view = {}
    for _ in range(2):
        fm = FleetMonitor(node=node, interval_s=0.2)
        # Port 9 (discard) with no listener: every connect refuses
        # instantly, so the ladder exhausts in milliseconds of sleep,
        # not sockets timing out.
        fm.add_peer("de" * 16, HttpObsClient("http://127.0.0.1:9"),
                    name="dead-peer")
        view = await fm.poll_once()
    wall = time.perf_counter() - t0
    row = view["nodes"].get("dead-peer") or {}
    return {
        "gave_up": _metric_value("sd_backoff_gave_up_total",
                                 name="obs.http") - gave_before,
        "row_stale": bool(row.get("stale")),
        "wall_s": round(wall, 3),
    }


async def _spacedrop_offers(node, count: int) -> Dict[str, Any]:
    """Spacedrop offers over real tunnels — needs the `cryptography`
    package (a second in-process node + pairing); recorded as skipped
    on stub-only containers."""
    try:
        import cryptography  # noqa: F401
    except ModuleNotFoundError:
        return {"skipped": "no cryptography in this container "
                           "(stub transports only)"}
    from spacedrive_tpu.node import Node

    tmp = tempfile.mkdtemp(prefix="sdtpu-load-drop-")
    peer = Node(os.path.join(tmp, "peer"))
    sent = 0
    try:
        if node.p2p is None:
            await node.start_p2p(host="127.0.0.1",
                                 enable_discovery=False)
        await peer.start()
        peer_port = await peer.start_p2p(host="127.0.0.1",
                                         enable_discovery=False)
        peer.p2p.on_spacedrop = \
            lambda _peer, req, _tmp=tmp: os.path.join(_tmp, "recv.bin")
        src = os.path.join(tmp, "payload.bin")

        def _write_payload() -> None:
            with open(src, "wb") as f:
                f.write(os.urandom(64 * 1024))

        await asyncio.to_thread(_write_payload)
        t0 = time.perf_counter()
        for _ in range(count):
            if await node.p2p.spacedrop(
                    "127.0.0.1", peer_port, src) == "sent":
                sent += 1
        wall = time.perf_counter() - t0
        return {"offers": count, "sent": sent,
                "wall_s": round(wall, 3)}
    finally:
        # Shielded: cleanup must finish even if the harness itself is
        # being cancelled mid-offer.
        await asyncio.shield(peer.shutdown())
        await asyncio.shield(asyncio.to_thread(
            shutil.rmtree, tmp, ignore_errors=True))


# -- counters / gate ---------------------------------------------------------

def _metric_value(family: str, **labels) -> float:
    m = telemetry.REGISTRY.get(family)
    if m is None:
        return 0.0
    if labels:
        m = m.labels(**labels)
    v = getattr(m, "value", None)
    return float(v) if v is not None else 0.0


def _counter_families() -> Dict[str, Any]:
    """The run's chaos/backoff/timeout/shed/busy evidence, filtered
    from the registry snapshot."""
    keep = ("sd_chaos_injected_total", "sd_backoff_retries_total",
            "sd_backoff_gave_up_total", "sd_timeout_fired_total",
            "sd_chan_shed_total", "sd_chan_high_water",
            "sd_store_busy_retries_total",
            "sd_sync_clone_pages_relayed_total",
            "sd_sync_clone_window_stalls_total",
            "sd_p2p_reconnects_total",
            "sd_wire_frames_total", "sd_wire_violations_total")
    snap = telemetry.snapshot()
    return {k: snap[k] for k in keep if k in snap}


def _declared_resource(res: str) -> bool:
    from spacedrive_tpu import timeouts

    if res in channels.CHANNELS or res in timeouts.TIMEOUTS \
            or res in timeouts.BACKOFFS or res == "node.process":
        return True
    return res.startswith((
        "store.db.", "store.actor.", "tasks.", "sanitize.",
        "ops.pipeline.", "fleet.peer.", "jobs."))


def _coalesce_wedges() -> List[str]:
    """Coalesce channels still FULL at quiescence — a permanently
    stuck consumer (the wedge gate)."""
    wedged = []
    m = telemetry.REGISTRY.get("sd_chan_depth")
    if m is None:
        return wedged
    for labels, child in m.samples():
        name = (labels or {}).get("name")
        c = channels.CHANNELS.get(name)
        if c is None or c.policy != "coalesce":
            continue
        if child.value >= channels.capacity(name):
            wedged.append(f"{name}: depth {child.value:g} at declared "
                          f"capacity {channels.capacity(name)} after "
                          "quiescence")
    return wedged


def _gate(doc: Dict[str, Any], fairness_floor: float) -> List[str]:
    failures: List[str] = []
    if doc["violations"]:
        failures.append(
            f"{len(doc['violations'])} sanitizer violation(s): "
            + "; ".join(v["kind"] for v in doc["violations"][:5]))
    failures.extend(doc["wedged_channels"])
    fair = doc["workloads"]["clone_burst"]["fairness"]
    if fair["ratio"] < fairness_floor:
        failures.append(
            f"clone starvation: slowest peer at {fair['ratio']:.2f}x "
            f"mean (floor {fairness_floor})")
    for sample in doc["health_samples"]:
        for sub, state in sample["states"].items():
            if state == "ok":
                continue
            entries = sample["attribution"].get(sub) or []
            named = [e for e in entries
                     if _declared_resource(e.get("resource", ""))]
            if not named:
                failures.append(
                    f"unattributed saturation: {sub}={state} in "
                    f"window '{sample.get('label')}' names no "
                    "declared resource")
    # Incident bundles the storm froze: every one must attribute a
    # DECLARED resource by name — a bundle naming nothing declared is
    # evidence the capture path lost the cause. (Their existence is
    # expected under chaos; only unattributed ones fail the gate.)
    for h in doc.get("incidents", {}).get("headers", []):
        trig = h.get("trigger") or {}
        if not _declared_resource(trig.get("resource", "")):
            failures.append(
                f"unattributed incident: {h.get('id')} "
                f"[{trig.get('kind')}] names undeclared resource "
                f"{trig.get('resource')!r}")
    return failures


# -- the run -----------------------------------------------------------------

async def run_bench(args) -> Dict[str, Any]:
    from spacedrive_tpu.api.server import ApiServer
    from spacedrive_tpu.node import Node

    tmp = tempfile.mkdtemp(prefix="sdtpu-load-")
    node = Node(os.path.join(tmp, "node"))
    server = None
    try:
        await node.start()
        # Narrowed admission window so bench-scale fan-in actually
        # exercises the shed edge (production keeps the declared 256).
        server = ApiServer(node,
                           http_inflight_cap=max(2, args.peers // 4))
        port = await server.start(port=0)
        lib = node.create_library("loadbench")
        seeded = await asyncio.to_thread(
            _seed_library, lib, args.waves, args.ops_per_wave)

        if args.chaos:
            chaos.arm(args.chaos, seed=args.seed)

        health = node.health
        health.sample()  # fresh cursor: each workload gets a window
        samples: List[Dict[str, Any]] = []

        def checkpoint(label: str) -> None:
            snap = dict(health.sample())
            snap["label"] = label
            samples.append(snap)

        workloads: Dict[str, Any] = {}

        pull_peers = [await asyncio.to_thread(
            _mk_peer_sync, tmp, f"pull{i}", lib.sync.instance)
            for i in range(args.peers)]
        workloads["pull_storm"] = await _pull_storm(lib, pull_peers)
        checkpoint("pull_storm")

        clone_peers = [await asyncio.to_thread(
            _mk_peer_sync, tmp, f"clone{i}", lib.sync.instance)
            for i in range(max(2, args.peers // 4))]
        workloads["clone_burst"] = await _clone_burst(
            lib, clone_peers, attempt_s=args.attempt_s)
        checkpoint("clone_burst")

        workloads["api_fanin"] = await _api_fanin(
            port, clients=args.peers, per_client=args.requests)
        checkpoint("api_fanin")

        workloads["ws_flood"] = await _ws_flood(
            node, port, subscribers=max(4, args.peers // 4),
            events=args.events)
        checkpoint("ws_flood")

        workloads["ingest_storm"] = await _ingest_storm(
            lib, pull_peers[:max(2, args.peers // 4)],
            ops_per_peer=args.ops_per_peer)
        checkpoint("ingest_storm")

        workloads["write_path_ab"] = await _write_path_ab(
            lib, pull_peers[:max(2, args.peers // 4)],
            ops_per_peer=max(4, args.ops_per_peer // 4))
        checkpoint("write_path_ab")

        workloads["spacedrop"] = await _spacedrop_offers(node, count=4)

        workloads["fleet_giveup"] = await _fleet_giveup(node)
        checkpoint("fleet_giveup")

        # Quiescence: disarm, let pumps drain, then the wedge check.
        chaos.disarm()
        await asyncio.sleep(0.3)
        checkpoint("quiescence")

        doc: Dict[str, Any] = {
            "bench": "load_bench",
            "schema": 1,
            "ts": time.time(),
            "config": {
                "peers": args.peers,
                "transport": "stub",
                "chaos": args.chaos or "",
                "seed": args.seed,
                "seed_ops": seeded,
                "waves": args.waves,
                "ops_per_wave": args.ops_per_wave,
                "fairness_floor": args.fairness_floor,
            },
            "workloads": workloads,
            "counters": _counter_families(),
            "health_samples": samples,
            # The black box's postmortem record of THIS storm: bundle
            # headers + per-fingerprint dedup counts (the node's
            # bootstrap installed the observatory; the full bundles
            # stay in its store until the tmp dir drops).
            "incidents": {
                "enabled": node.incidents is not None,
                "headers": node.incidents.list()
                if node.incidents is not None else [],
                "deduped": node.incidents.deduped()
                if node.incidents is not None else {},
            },
            "wedged_channels": _coalesce_wedges(),
            "violations": sanitize.violations(),
        }
        doc["gate"] = {"failures": _gate(doc, args.fairness_floor)}
        doc["gate"]["passed"] = not doc["gate"]["failures"]
        return doc
    finally:
        chaos.disarm()
        # Shielded: a cancelled run must still reap the node's task
        # tree and drop the multi-GB peer corpus.
        if server is not None:
            await asyncio.shield(server.stop())
        await asyncio.shield(node.shutdown())
        await asyncio.shield(asyncio.to_thread(
            shutil.rmtree, tmp, ignore_errors=True))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet-scale load + chaos harness (one real node, "
                    "N stub peers)")
    ap.add_argument("--peers", type=int, default=32)
    ap.add_argument("--waves", type=int, default=2,
                    help="seed blob-page waves in the origin library")
    ap.add_argument("--ops-per-wave", type=int, default=256)
    ap.add_argument("--requests", type=int, default=12,
                    help="API fan-in requests per client")
    ap.add_argument("--events", type=int, default=400,
                    help="ws-flood EventBus notifications")
    ap.add_argument("--ops-per-peer", type=int, default=64,
                    help="ingest-storm ops authored per pushing peer")
    ap.add_argument("--attempt-s", type=float, default=30.0,
                    help="wall bound per clone stream attempt")
    ap.add_argument("--chaos", default=DEFAULT_CHAOS,
                    help="chaos.py spec to arm for the run "
                         "('' = disarmed)")
    # Default seed chosen so the default spec fires at least one
    # mid-clone disconnect inside the burst's first window — the
    # recorded artifact must demonstrate reconnect recovery, not luck
    # its way past it.
    ap.add_argument("--seed", type=int, default=4242)
    ap.add_argument("--fairness-floor", type=float, default=0.25)
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH",
                    help="write the BENCH artifact (- = stdout)")
    ap.add_argument("--gate", action="store_true",
                    help="exit non-zero on wedge/starvation/"
                         "unattributed saturation/violations")
    args = ap.parse_args(argv)

    # Count-mode sanitizer: the gate asserts ZERO recorded violations
    # without a mid-storm raise tearing the run down half-measured.
    os.environ.setdefault("SDTPU_SANITIZE", "1")
    os.environ.setdefault("SDTPU_SANITIZE_MODE", "count")
    sanitize.install()

    doc = asyncio.run(run_bench(args))

    if args.json:
        payload = json.dumps(doc, indent=2, default=str)
        if args.json == "-":
            print(payload)
        else:
            from spacedrive_tpu import persist

            persist.atomic_write("bench.artifact", args.json,
                                 payload + "\n")
    summary = {w: {k: v for k, v in row.items()
                   if not isinstance(v, (list, dict))}
               for w, row in doc["workloads"].items()
               if isinstance(row, dict)}
    print("load_bench:", json.dumps(summary), file=sys.stderr)
    for fail in doc["gate"]["failures"]:
        print(f"GATE FAIL: {fail}", file=sys.stderr)
    print(f"gate: {'PASS' if doc['gate']['passed'] else 'FAIL'} "
          f"(chaos={doc['config']['chaos'] or 'disarmed'})",
          file=sys.stderr)
    if args.gate and not doc["gate"]["passed"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
