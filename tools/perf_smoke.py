"""End-to-end perf smoke: the BASELINE.md benchmark configs, runnable.

Generates a deterministic corpus (tools/make_corpus.py), runs the real
pipeline through the real job system — index → identify → validate →
exact-dup — and prints one JSON line per stage with files/sec. This is
the workload-level complement to bench.py's kernel-level number
(BASELINE.json configs 1–3; config 4 runs when images are requested,
config 5 is this with --files 1000000 across multiple locations).

    python tools/perf_smoke.py --files 10000 [--backend auto] [--images 300]

--telemetry resets the node-wide metrics registry before the run and
sources the identify stage's hash-vs-host phase_split from the SAME
`sd_identifier_phase_seconds_total` counters production serves on
GET /metrics (instead of the job report's metadata), then appends a
final {"stage": "telemetry"} line with the full registry snapshot.
--json PATH additionally writes every stage line (and the snapshot,
when --telemetry is on) as one BENCH_r*-style artifact.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _registry_phase_split():
    """The identify hash-vs-host split, read from the SAME registry
    counters GET /metrics serves (sd_identifier_phase_seconds_total) —
    production-visible numbers, not job-report metadata."""
    from spacedrive_tpu import telemetry

    fam = telemetry.snapshot().get(
        "sd_identifier_phase_seconds_total", {})
    phases = {e["labels"]["phase"]: float(e["value"])
              for e in fam.get("labeled", [])}
    hash_ms = phases.get("hash", 0.0) * 1000.0
    stage_ms = phases.get("prep", 0.0) * 1000.0
    host_ms = sum(v for k, v in phases.items()
                  if k not in ("hash", "prep", "step_total",
                               "overlap_wait")) * 1000.0
    total = hash_ms + stage_ms + host_ms
    if not total:
        return None
    return {
        "hash_ms": round(hash_ms, 1),
        "stage_ms": round(stage_ms, 1),
        "host_ms": round(host_ms, 1),
        "host_pct": round(100.0 * host_ms / total, 1),
        "source": "registry",
    }


async def run(files: int, backend: str, images: int, keep: str | None,
              device_batch: int | None = None, small: bool = False,
              validate_backend: str | None = None,
              with_telemetry: bool = False, json_out: str = "",
              trace_out: str = ""):
    from tools.make_corpus import make_corpus

    from spacedrive_tpu import telemetry
    from spacedrive_tpu.jobs.report import JobStatus
    from spacedrive_tpu.locations.indexer_job import IndexerJob
    from spacedrive_tpu.locations.manager import create_location
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.objects.dedup import exact_duplicate_groups
    from spacedrive_tpu.objects.identifier import FileIdentifierJob
    from spacedrive_tpu.objects.validator import ObjectValidatorJob

    lines: list = []
    health_problems: list = []

    def emit(line: dict) -> None:
        lines.append(line)
        print(json.dumps(line), flush=True)

    monitor = None
    if with_telemetry:
        # The artifact should cover THIS run only, not whatever the
        # process did before (the registry is process-global).
        telemetry.reset()
        # Arm the SQL auditor in COUNT mode so the `sql` stage carries
        # per-statement counts and the tx histogram on unsanitized
        # bench runs (violations count, never raise). Before any
        # Database opens — the factory is read per connection.
        from spacedrive_tpu import sanitize
        from spacedrive_tpu.store import sqlaudit

        if not sqlaudit.armed():
            sqlaudit.arm("count", sanitize.record)
        # Whole-run health window: cursors established here, sampled
        # once at the end — the artifact's `health` stage shows what
        # saturated DURING the run, next to the numbers it explains.
        from spacedrive_tpu.health import HealthMonitor

        monitor = HealthMonitor()
    if trace_out:
        # Same per-run hygiene for the flight recorder: the exported
        # timeline + span ring should cover this run only.
        from spacedrive_tpu import flight, tracing

        flight.RECORDER.clear()
        tracing.clear_span_ring()

    from spacedrive_tpu import persist

    # Bench harness: blocking corpus teardown on the (idle) loop
    # at exit is the measured run's own cleanup.
    # sdlint: ok[blocking-async]
    with persist.scratch("bench.workdir", keep=keep) as root:
        corpus = os.path.join(root, "corpus")
        t0 = time.perf_counter()
        stats = make_corpus(corpus, files=files, dup_rate=0.1, images=images,
                            small_only=small)
        emit({"stage": "corpus", "seconds":
              round(time.perf_counter() - t0, 2), **stats})

        node = Node(os.path.join(root, "data"))
        await node.start()
        lib = node.create_library("perf")
        loc = create_location(lib, corpus)

        async def stage(name, job):
            t0 = time.perf_counter()
            jid = await node.jobs.ingest(lib, job)
            status = await node.jobs.wait(jid)
            dt = time.perf_counter() - t0
            assert status in (JobStatus.COMPLETED,
                              JobStatus.COMPLETED_WITH_ERRORS), (name, status)
            n = lib.db.run("bench.file_count")["n"]
            line = {
                "stage": name, "seconds": round(dt, 2),
                "files": n, "files_per_sec": round(n / dt, 1),
                "status": int(status),
            }
            from spacedrive_tpu.jobs.report import JobReport
            row = lib.db.run("jobs.report.by_id", (jid,))
            report = JobReport.from_row(row) if row else None
            if report and report.metadata.get("phase_ms"):
                # Where the ms/file goes (fetch/prep/hash/db/ops), summed
                # over all chunks — the e2e profile, not the kernel number.
                pm = report.metadata["phase_ms"]
                line["phase_ms"] = pm
                line["chunk_size"] = report.metadata.get("chunk_size")
                # The hash-vs-host split as a tracked artifact: how much of
                # the accounted COST is hashing versus host-side
                # serialization (op log, domain writes, commits, paging) —
                # the ratio the op-log work is judged by, printed per run
                # instead of reconstructed from README prose. Phases are
                # true per-phase costs even when overlapped (the identifier
                # merges worker-measured times and books the consumer's
                # stall separately as overlap_wait), so this is cost
                # attribution, not a wall-clock partition.
                hash_ms = pm.get("hash", 0.0)
                stage_ms = pm.get("prep", 0.0)  # hashing-pipeline staging
                host_ms = sum(v for k, v in pm.items()
                              if k not in ("hash", "prep", "step_total",
                                           "overlap_wait"))
                total = hash_ms + stage_ms + host_ms
                if total:
                    line["phase_split"] = {
                        "hash_ms": round(hash_ms, 1),
                        "stage_ms": round(stage_ms, 1),
                        "host_ms": round(host_ms, 1),
                        "host_pct": round(100.0 * host_ms / total, 1),
                    }
            if with_telemetry and name == "identify":
                # Same split, sourced from the live registry counters the
                # /metrics endpoint serves — the production-visible number.
                reg_split = _registry_phase_split()
                if reg_split:
                    line["phase_split"] = reg_split
            emit(line)
            return dt

        await stage("index", IndexerJob(location_id=loc))
        await stage("identify", FileIdentifierJob(location_id=loc,
                                                  backend=backend,
                                                  device_batch=device_batch))
        await stage("validate", ObjectValidatorJob(
            location_id=loc, backend=validate_backend or "auto"))
        if validate_backend:
            # Second pass in verify mode re-hashes everything through the
            # SAME backend, giving a workload-level files/s figure for the
            # sequence-sharded device plane (VERDICT r2 item 9) — the fill
            # pass above already consumed the NULL checksums.
            await stage(f"validate_{validate_backend}_verify",
                        ObjectValidatorJob(location_id=loc,
                                           backend=validate_backend,
                                           mode="verify"))

        t0 = time.perf_counter()
        groups = exact_duplicate_groups(lib, location_id=loc)
        emit({
            "stage": "exact_dup", "seconds":
            round(time.perf_counter() - t0, 2),
            "duplicate_groups": len(groups),
        })

        if images:
            from spacedrive_tpu.objects.dedup import NearDupDetectorJob

            await stage("near_dup",
                        NearDupDetectorJob(location_id=loc, threshold=10))
            near = lib.db.run("bench.phash_count")["n"]
            pairs = lib.db.run("bench.pair_count")["n"]
            emit({"stage": "near_dup_hashed", "hashed_images": near,
                  "near_dup_pairs": pairs})

        n_objects = lib.db.run("store.object_count")["n"]
        n_paths = lib.db.run("bench.identified_count")["n"]
        emit({
            "stage": "summary", "identified_paths": n_paths,
            "objects": n_objects,
            "dedup_collapsed": n_paths - n_objects,
        })
        await node.shutdown()
        if with_telemetry:
            # The full registry snapshot — the same counters /metrics and
            # node.metrics serve — embedded so future perf PRs report phase
            # splits from production telemetry, not ad-hoc prints.
            emit({"stage": "telemetry", "metrics": telemetry.snapshot()})
            # Compile-stability proof for the artifact: per-contract trace
            # counts vs their declared budgets (ops/jit_registry.py). A
            # bench run whose jit section shows counts ≤ budget proves the
            # identify pipeline hit only canonical shapes — no silent
            # recompiles hiding in the measured wall.
            from spacedrive_tpu.ops import jit_registry

            traces = jit_registry.trace_counts()
            emit({"stage": "jit", "traces": traces, "budgets": {
                name: jit_registry.CONTRACTS[name].max_traces
                for name in traces
            }, "over_budget": sorted(
                name for name, n in traces.items()
                if n > jit_registry.CONTRACTS[name].max_traces)})
            # Pipeline-shape proof next to the jit stage: the depth-N ring's
            # registry families (depth high-water, stall seconds, H2D
            # bytes/seconds, donated-buffer reuse, per-device batch split)
            # plus the configured depth — so a bench artifact shows HOW the
            # identify stream was fed, not just how fast it went.
            from spacedrive_tpu.ops import overlap as overlap_mod

            snap = telemetry.snapshot()
            emit({"stage": "pipeline",
                  "depth_configured": overlap_mod.pipeline_depth(),
                  "metrics": {name: value for name, value in snap.items()
                              if name.startswith(("sd_pipeline_",
                                                  "sd_stage_pool_"))}})
            # Saturation evidence next to the numbers: subsystem states +
            # top attribution over the WHOLE run's window (the monitor's
            # cursors were established before the corpus stage), schema-
            # gated like the trace artifact.
            from spacedrive_tpu import health as health_mod

            hsnap = monitor.sample()
            health_problems.extend(
                health_mod.validate_health_snapshot(hsnap))
            for p in health_problems:
                print(f"HEALTH SCHEMA: {p}", file=sys.stderr)
            emit({"stage": "health",
                  "window_s": hsnap["window_s"],
                  "states": hsnap["states"],
                  "attribution": hsnap["attribution"]})
            # Store-seam evidence (round 16): which declared statements
            # the run actually executed, by count and by rows, plus the
            # per-tx statement histogram — a commit-per-item regression
            # in any job shows up RIGHT HERE as a 1-2-statement spike.
            from spacedrive_tpu.store import sqlaudit

            emit({"stage": "sql", **sqlaudit.stage_summary()})
        if json_out:
            # One small artifact at teardown; the measured stages
            # are over.
            # sdlint: ok[blocking-async]
            persist.atomic_write("bench.artifact", json_out, json.dumps({
                "metric": "perf_smoke",
                "files": files, "backend": backend,
                "telemetry_enabled": with_telemetry,
                "stages": lines,
            }, indent=1))
        trace_problems: list = []
        if trace_out:
            # The run's flight-recorder export: job/rpc spans + identify
            # timeline lanes as one Chrome-trace artifact next to the
            # BENCH JSON. Schema-gated (shared write_trace_artifact
            # helper) so a malformed trace fails the bench run, not the
            # person opening it later.
            from spacedrive_tpu import flight

            trace_problems = await asyncio.to_thread(
                flight.write_trace_artifact, trace_out, "perf_smoke")
            for p in trace_problems:
                print(f"TRACE SCHEMA: {p}", file=sys.stderr)
            if not trace_problems:
                print(f"trace artifact: {trace_out}", file=sys.stderr)
    if trace_problems or health_problems:
        # Exit non-zero AFTER the scratch cleanup above: a schema
        # regression must fail the run, not also leak a multi-GB
        # sdtpu-perf-* tempdir per attempt.
        raise SystemExit(1)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=10000)
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--device-batch", type=int, default=None)
    ap.add_argument("--images", type=int, default=0)
    ap.add_argument("--keep", help="reuse/keep this directory")
    ap.add_argument("--small", action="store_true",
                    help="small files only (100k/1M-scale runs)")
    ap.add_argument("--validate-backend", default=None,
                    choices=("jax", "native", "oracle"),
                    help="pin the validator backend and add a verify-mode "
                         "pass timed on it (e.g. jax on a virtual mesh)")
    ap.add_argument("--virtual-devices", type=int, default=0,
                    help="force a CPU platform with N virtual devices "
                         "(the multi-chip test mesh) before any jax use")
    ap.add_argument("--telemetry", action="store_true",
                    help="reset the metrics registry, source the "
                         "identify phase split from it, and append the "
                         "registry snapshot to the output")
    ap.add_argument("--json", default="", metavar="PATH",
                    help="write all stage lines (+ telemetry snapshot) "
                         "as one BENCH-style JSON artifact")
    ap.add_argument("--trace", default="", metavar="PATH",
                    help="export the run's flight-recorder timeline + "
                         "span ring as a schema-validated Chrome-trace "
                         "JSON artifact")
    args = ap.parse_args()
    if args.virtual_devices:
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.virtual_devices} "
            + os.environ.get("XLA_FLAGS", ""))
        import jax

        # The axon plugin overrides JAX_PLATFORMS at interpreter start;
        # the config update below is the only reliable CPU pin.
        jax.config.update("jax_platforms", "cpu")
    asyncio.run(run(args.files, args.backend, args.images, args.keep,
                    args.device_batch, args.small, args.validate_backend,
                    with_telemetry=args.telemetry, json_out=args.json,
                    trace_out=args.trace))
