"""sd_top — the health observatory's live operator top.

Polls a live node's `node.health` (and renders what its sampler
already computed: per-subsystem saturation states, bottleneck
attribution with the declared resource names, channel depths vs
declared capacities, windowed p99s and rates) — the "what is
saturated and what is it blocked on" view `/metrics` alone cannot
give.

    python -m tools.sd_top --url http://host:port           # live top
    python -m tools.sd_top --url http://host:port --once    # one frame
    python -m tools.sd_top --url http://host:port --json    # one-shot artifact
    python -m tools.sd_top --json [--out PATH]              # self-check
    python -m tools.sd_top --input artifact.json            # validate only

- `--json` without `--url` runs the built-in SELF-CHECK: three
  synthetic saturations (a shedding channel, a slow store write lock,
  a fired timeout budget) are driven through the real registry and a
  real HealthMonitor, the resulting artifact is schema-validated
  (`health.validate_health_snapshot`) AND semantically checked (each
  induced saturation must be attributed to the right declared
  resource). Non-zero exit on any violation — tier-1 runs this so the
  observatory cannot rot silently, same pattern as
  `trace_export.py --json`.
- `--url` attaches to a live node over rspc HTTP; every fetched
  snapshot is validated before rendering (a malformed one exits 1).
- `--input` validates a stored artifact (CI gating).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STATE_MARK = {"ok": " ", "degraded": "!", "saturated": "#"}


def _fetch_rspc(url: str, path: str) -> dict:
    endpoint = url.rstrip("/") + "/rspc/" + path
    with urllib.request.urlopen(endpoint, timeout=30) as resp:
        payload = json.load(resp)
    result = payload.get("result") if isinstance(payload, dict) else None
    if result is None:
        raise SystemExit(f"no result in response from {endpoint}")
    return result


def fetch_health(url: str) -> dict:
    """GET /rspc/node.health from a live node's API host."""
    return _fetch_rspc(url, "node.health")


def fetch_metrics(url: str) -> dict:
    """GET /rspc/node.metrics — the cumulative registry next to the
    windowed health view (same counters `/metrics` scrapes)."""
    return _fetch_rspc(url, "node.metrics")


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def render_top(snap: dict, source: str = "", width: int = 100,
               metrics: dict = None) -> str:
    """One text frame over a HealthSnapshot (plus, when the caller
    polled node.metrics too, cumulative context in the header):
    states + attribution, channel depths, windowed p99s, hottest
    rates."""
    out = []
    ts = time.strftime("%H:%M:%S", time.localtime(snap.get("ts", 0)))
    header = (
        f"sd_top — {source or 'node'}  ts={ts}  "
        f"window={_fmt(snap.get('window_s'))}s  "
        f"tasks={snap.get('tasks', {}).get('live', '-')}")
    if metrics:
        tx = metrics.get("sd_store_tx_total", {}).get("value")
        header += (f"  families={len(metrics)}"
                   + (f"  tx_total={_fmt(tx)}" if tx is not None else ""))
    out.append(header)
    out.append("")
    out.append(f"{'SUBSYSTEM':<10} {'STATE':<10} BOTTLENECK")
    attribution = snap.get("attribution", {})
    for sub in sorted(snap.get("states", {})):
        st = snap["states"][sub]
        entries = attribution.get(sub, [])
        top = ""
        if entries:
            e = entries[0]
            ev = ", ".join(
                f"{k.split('{')[0]}={_fmt(v)}"
                for k, v in list(e.get("evidence", {}).items())[:3])
            top = f"{e['resource']} — {e['reason']}"
            if ev:
                top += f"  [{ev}]"
        line = f"{STATE_MARK.get(st, '?')}{sub:<9} {st:<10} {top}"
        out.append(line[:width])
        for e in entries[1:]:
            out.append(f"  {'':<19} {e['resource']} — "
                       f"{e['reason']}"[:width])
    window = snap.get("window", {})
    chans = [(rec["labels"].get("name", "?"), rec.get("value", 0))
             for rec in window.values()
             if rec.get("family") == "sd_chan_depth"]
    if chans:
        out.append("")
        out.append("CHANNELS (depth / shed rate):")
        for name, depth in sorted(chans):
            shed = window.get(
                f"sd_chan_shed_total{{name={name}}}", {})
            out.append(f"  {name:<28} depth={_fmt(depth):<8} "
                       f"shed/s={_fmt(shed.get('rate', 0))}")
    hists = [(k, rec) for k, rec in window.items()
             if rec.get("kind") == "histogram"
             and (rec.get("count") or 0) > 0]
    if hists:
        out.append("")
        out.append("WINDOWED LATENCIES (p50 / p95 / p99, this window):")
        hists.sort(key=lambda kv: -(kv[1].get("p99") or 0))
        for k, rec in hists[:12]:
            out.append(
                f"  {k[:44]:<44} {_fmt(rec.get('p50'))} / "
                f"{_fmt(rec.get('p95'))} / {_fmt(rec.get('p99'))}  "
                f"(n={rec.get('count')})")
    rates = [(k, rec.get("rate") or 0) for k, rec in window.items()
             if rec.get("kind") == "counter" and (rec.get("rate") or 0) > 0]
    if rates:
        out.append("")
        out.append("HOTTEST RATES (/s, this window):")
        rates.sort(key=lambda kv: -kv[1])
        for k, r in rates[:12]:
            out.append(f"  {k[:60]:<60} {_fmt(r)}")
    return "\n".join(out)


def build_self_check() -> dict:
    """Drive three KNOWN saturations through the real registry and a
    real HealthMonitor, so the artifact exercises every schema shape:
    channel shed, store write-lock wait, and a fired timeout budget."""
    from spacedrive_tpu import channels, health, telemetry
    from spacedrive_tpu.telemetry import (
        STORE_WRITE_LOCK_WAIT_SECONDS,
        TIMEOUTS_FIRED,
    )

    monitor = health.HealthMonitor(interval_s=0.05)
    # 1. a shedding channel (tools-owned bench contract, shed_new)
    ch = channels.channel("bench.shed")
    for i in range(2 * ch.capacity):
        ch.put_nowait(i)
    # 2. a held store write lock's wait histogram
    STORE_WRITE_LOCK_WAIT_SECONDS.observe(0.8)
    # 3. a declared network budget firing
    TIMEOUTS_FIRED.labels(name="p2p.ping").inc()
    time.sleep(0.06)  # a real (if tiny) window for the rates
    snap = monitor.sample()
    del telemetry
    return {
        "metric": "sd_top",
        "source": "self-check",
        "health": snap,
    }


def self_check_problems(artifact: dict) -> list:
    """Schema + semantic gate over the self-check artifact: the three
    induced saturations must be attributed to the right declared
    resources by name."""
    from spacedrive_tpu import health

    snap = artifact.get("health", {})
    problems = health.validate_health_snapshot(snap)
    attribution = snap.get("attribution", {})

    def attributed(sub: str, resource: str) -> bool:
        return any(e.get("resource") == resource
                   for e in attribution.get(sub, []))

    if not attributed("bench", "bench.shed"):
        problems.append(
            "self-check: shedding bench.shed channel not attributed")
    if not attributed("store", "store.db.write_lock"):
        problems.append(
            "self-check: write-lock wait not attributed to "
            "store.db.write_lock")
    if not attributed("p2p", "p2p.ping"):
        problems.append(
            "self-check: fired p2p.ping budget not attributed")
    if snap.get("states", {}).get("store") != "saturated":
        problems.append("self-check: store state not saturated")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live operator top / health-artifact gate")
    ap.add_argument("--url", default="", metavar="http://host:port",
                    help="attach to a live node's rspc host")
    ap.add_argument("--json", action="store_true",
                    help="emit one schema-validated JSON artifact "
                         "(without --url: run the built-in self-check; "
                         "exit 1 on any violation)")
    ap.add_argument("--input", default="", metavar="PATH",
                    help="validate an existing sd_top JSON artifact")
    ap.add_argument("--out", default="", metavar="PATH",
                    help="write the (validated) artifact here")
    ap.add_argument("--once", action="store_true",
                    help="render one frame instead of polling")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll seconds in live mode (default 2)")
    args = ap.parse_args(argv)

    from spacedrive_tpu import health

    if args.input:
        try:
            with open(args.input, encoding="utf-8") as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"sd_top: unreadable {args.input}: {e}",
                  file=sys.stderr)
            return 1
        problems = health.validate_health_snapshot(
            artifact.get("health", artifact))
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"sd_top: valid ({args.input})")
        return 0

    if args.json and not args.url:
        artifact = build_self_check()
        problems = self_check_problems(artifact)
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            print(f"sd_top: {len(problems)} violation(s)",
                  file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=1)
            print(f"sd_top: wrote {args.out}", file=sys.stderr)
        print(json.dumps(artifact))
        return 0

    if not args.url:
        ap.error("--url is required outside --json/--input modes")

    while True:
        snap = fetch_health(args.url)
        problems = health.validate_health_snapshot(snap)
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        if args.json:
            artifact = {"metric": "sd_top", "source": args.url,
                        "health": snap}
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    json.dump(artifact, f, indent=1)
            print(json.dumps(artifact))
            return 0
        try:
            metrics = fetch_metrics(args.url)
        except Exception:
            metrics = None  # health alone still renders
        frame = render_top(snap, source=args.url, metrics=metrics)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    sys.exit(main())
