"""sd_top — the health observatory's live operator top.

Polls a live node's `node.health` (and renders what its sampler
already computed: per-subsystem saturation states, bottleneck
attribution with the declared resource names, channel depths vs
declared capacities, windowed p99s and rates) — the "what is
saturated and what is it blocked on" view `/metrics` alone cannot
give.

    python -m tools.sd_top --url http://host:port           # live top
    python -m tools.sd_top --url http://host:port --once    # one frame
    python -m tools.sd_top --url http://host:port --json    # one-shot artifact
    python -m tools.sd_top --json [--out PATH]              # self-check
    python -m tools.sd_top --input artifact.json            # validate only
    python -m tools.sd_top --fleet --url http://host:port   # fleet matrix
    python -m tools.sd_top --fleet --json                   # 2-node self-check

- `--json` without `--url` runs the built-in SELF-CHECK: three
  synthetic saturations (a shedding channel, a slow store write lock,
  a fired timeout budget) are driven through the real registry and a
  real HealthMonitor, the resulting artifact is schema-validated
  (`health.validate_health_snapshot`) AND semantically checked (each
  induced saturation must be attributed to the right declared
  resource). Non-zero exit on any violation — tier-1 runs this so the
  observatory cannot rot silently, same pattern as
  `trace_export.py --json`.
- `--url` attaches to a live node over rspc HTTP; every fetched
  snapshot is validated before rendering (a malformed one exits 1).
- `--input` validates a stored artifact (CI gating).
- `--fleet` switches every mode to the fleet observatory: live/once/
  json render the merged per-(node, subsystem) matrix from
  `fleet.health`; `--fleet --json` without `--url` runs the 2-NODE
  SELF-CHECK — a real second node process (tools/fleet_peer.py, its
  own registry/span ring) is booted with seeded saturations, polled
  over the obs protocol, and the artifact must attribute each seeded
  saturation to the right declared resource ON THE REMOTE ROW (and
  not on the local one), plus assemble one schema-clean two-lane
  fleet trace under a single trace id. Non-zero exit on any
  violation — the tier-1 gate for the whole federation plane.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STATE_MARK = {"ok": " ", "degraded": "!", "saturated": "#"}


def _fetch_rspc(url: str, path: str) -> dict:
    endpoint = url.rstrip("/") + "/rspc/" + path
    with urllib.request.urlopen(endpoint, timeout=30) as resp:
        payload = json.load(resp)
    result = payload.get("result") if isinstance(payload, dict) else None
    if result is None:
        raise SystemExit(f"no result in response from {endpoint}")
    return result


def fetch_health(url: str) -> dict:
    """GET /rspc/node.health from a live node's API host."""
    return _fetch_rspc(url, "node.health")


def fetch_metrics(url: str) -> dict:
    """GET /rspc/node.metrics — the cumulative registry next to the
    windowed health view (same counters `/metrics` scrapes)."""
    return _fetch_rspc(url, "node.metrics")


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def render_top(snap: dict, source: str = "", width: int = 100,
               metrics: dict = None) -> str:
    """One text frame over a HealthSnapshot (plus, when the caller
    polled node.metrics too, cumulative context in the header):
    states + attribution, channel depths, windowed p99s, hottest
    rates."""
    out = []
    ts = time.strftime("%H:%M:%S", time.localtime(snap.get("ts", 0)))
    header = (
        f"sd_top — {source or 'node'}  ts={ts}  "
        f"window={_fmt(snap.get('window_s'))}s  "
        f"tasks={snap.get('tasks', {}).get('live', '-')}")
    if metrics:
        tx = metrics.get("sd_store_tx_total", {}).get("value")
        header += (f"  families={len(metrics)}"
                   + (f"  tx_total={_fmt(tx)}" if tx is not None else ""))
    out.append(header)
    out.append("")
    out.append(f"{'SUBSYSTEM':<10} {'STATE':<10} BOTTLENECK")
    attribution = snap.get("attribution", {})
    for sub in sorted(snap.get("states", {})):
        st = snap["states"][sub]
        entries = attribution.get(sub, [])
        top = ""
        if entries:
            e = entries[0]
            ev = ", ".join(
                f"{k.split('{')[0]}={_fmt(v)}"
                for k, v in list(e.get("evidence", {}).items())[:3])
            top = f"{e['resource']} — {e['reason']}"
            if ev:
                top += f"  [{ev}]"
        line = f"{STATE_MARK.get(st, '?')}{sub:<9} {st:<10} {top}"
        out.append(line[:width])
        for e in entries[1:]:
            out.append(f"  {'':<19} {e['resource']} — "
                       f"{e['reason']}"[:width])
    window = snap.get("window", {})
    chans = [(rec["labels"].get("name", "?"), rec.get("value", 0))
             for rec in window.values()
             if rec.get("family") == "sd_chan_depth"]
    if chans:
        out.append("")
        out.append("CHANNELS (depth / shed rate):")
        for name, depth in sorted(chans):
            shed = window.get(
                f"sd_chan_shed_total{{name={name}}}", {})
            out.append(f"  {name:<28} depth={_fmt(depth):<8} "
                       f"shed/s={_fmt(shed.get('rate', 0))}")
    hists = [(k, rec) for k, rec in window.items()
             if rec.get("kind") == "histogram"
             and (rec.get("count") or 0) > 0]
    if hists:
        out.append("")
        out.append("WINDOWED LATENCIES (p50 / p95 / p99, this window):")
        hists.sort(key=lambda kv: -(kv[1].get("p99") or 0))
        for k, rec in hists[:12]:
            out.append(
                f"  {k[:44]:<44} {_fmt(rec.get('p50'))} / "
                f"{_fmt(rec.get('p95'))} / {_fmt(rec.get('p99'))}  "
                f"(n={rec.get('count')})")
    rates = [(k, rec.get("rate") or 0) for k, rec in window.items()
             if rec.get("kind") == "counter" and (rec.get("rate") or 0) > 0]
    if rates:
        out.append("")
        out.append("HOTTEST RATES (/s, this window):")
        rates.sort(key=lambda kv: -kv[1])
        for k, r in rates[:12]:
            out.append(f"  {k[:60]:<60} {_fmt(r)}")
    return "\n".join(out)


def fetch_fleet(url: str) -> dict:
    """GET /rspc/fleet.health from a live node's API host."""
    return _fetch_rspc(url, "fleet.health")


def render_fleet(view: dict, source: str = "", width: int = 110) -> str:
    """One text frame over a merged fleet view: a per-node liveness
    header, then the (node, subsystem) state/attribution matrix."""
    out = []
    ts = time.strftime("%H:%M:%S", time.localtime(view.get("ts", 0)))
    nodes = view.get("nodes", {})
    out.append(f"sd_top --fleet — {source or 'fleet'}  ts={ts}  "
               f"nodes={len(nodes)}  "
               f"interval={_fmt(view.get('interval_s'))}s")
    out.append("")
    out.append(f"{'NODE':<14} {'REACH':<7} {'AGE':<8} {'RTT':<9} "
               f"{'SKEW':<10} {'INC':<7} ERROR")
    now = view.get("ts", time.time())
    for name, row in sorted(nodes.items(),
                            key=lambda kv: (not kv[1]["local"], kv[0])):
        age = (f"{now - row['last_seen']:.1f}s"
               if row.get("last_seen") else "-")
        reach = "local" if row.get("local") else (
            "ok" if row.get("reachable") else "STALE")
        rtt = f"{row['rtt_s'] * 1e3:.1f}ms" \
            if row.get("rtt_s") is not None else "-"
        skew = f"{row['skew_s'] * 1e3:+.1f}ms" \
            if row.get("skew_s") is not None else "-"
        # Incident digest: open(unacked)/total frozen bundles on that
        # node — the "which node has an untriaged postmortem" column.
        incd = row.get("incidents") or {}
        inc = (f"{incd.get('open', 0)}/{incd.get('total', 0)}"
               if incd else "-")
        out.append(f"{name[:14]:<14} {reach:<7} {age:<8} {rtt:<9} "
                   f"{skew:<10} {inc:<7} "
                   f"{row.get('error') or ''}"[:width])
    out.append("")
    out.append(f"{'NODE':<14} {'SUBSYSTEM':<10} {'STATE':<10} "
               "BOTTLENECK")
    for name, row in sorted(nodes.items(),
                            key=lambda kv: (not kv[1]["local"], kv[0])):
        attribution = row.get("attribution", {})
        for sub in sorted(row.get("states", {})):
            st = row["states"][sub]
            entries = attribution.get(sub, [])
            top = ""
            if entries:
                e = entries[0]
                top = f"{e['resource']} — {e['reason']}"
            mark = STATE_MARK.get(st, "?")
            out.append(f"{name[:14]:<14} {mark}{sub:<9} {st:<10} "
                       f"{top}"[:width])
    return "\n".join(out)


def build_fleet_self_check() -> dict:
    """The 2-node fleet gate: boot a REAL second node process
    (tools/fleet_peer.py — its own registry, span ring, flight
    recorder) with seeded saturations, poll it over the obs protocol,
    merge the fleet view, and assemble a two-lane trace under one
    trace id shared by both processes."""
    import asyncio
    import subprocess
    import threading
    import uuid as uuidlib

    from spacedrive_tpu import health, tracing
    from spacedrive_tpu.fleet import FleetMonitor, HttpObsClient

    trace_id = f"{uuidlib.uuid4().int & ((1 << 63) - 1) | 1:x}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "tools.fleet_peer",
         "--name", "peer-b", "--trace", trace_id],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        # Bounded handshake read: a peer that wedges during boot must
        # fail THIS gate fast, not park it on readline until the
        # outer CI timeout.
        box = {}
        reader = threading.Thread(
            target=lambda: box.__setitem__(
                "line", proc.stdout.readline()),
            daemon=True)
        reader.start()
        reader.join(timeout=120)
        line = box.get("line", "")
        if not line.strip():
            raise SystemExit(
                "sd_top: fleet peer failed to boot (no handshake "
                "line within 120s)")
        peer = json.loads(line)

        # Local half: loose monitors (no full node needed) plus spans
        # recorded under the SAME trace id the peer seeded.
        local_health = health.HealthMonitor(
            interval_s=0.05, node_id="sd-top-local",
            node_name="sd-top")
        with tracing.continue_trace(f"{trace_id}-2"):
            with tracing.span("rpc/fleet.selfCheck"):
                pass
        monitor = FleetMonitor(
            interval_s=0.5, node_id="sd-top-local",
            node_name="sd-top", health=local_health)
        monitor.add_peer(
            peer["id"], HttpObsClient(f"http://127.0.0.1:{peer['port']}"),
            name=peer["name"])

        async def run():
            view = await monitor.poll_once()
            doc = await monitor.assemble_trace(trace_id)
            return view, doc

        view, doc = asyncio.run(run())
        return {
            "metric": "sd_top_fleet",
            "source": "self-check",
            "peer": peer,
            "trace_id": trace_id,
            "fleet": view,
            "trace": doc,
        }
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=20)
        except Exception:
            proc.kill()


def fleet_self_check_problems(artifact: dict) -> list:
    """Schema + semantic gate over the 2-node artifact: the remote row
    must carry each seeded saturation attributed by declared resource
    name (and the LOCAL row must not — separate registries is the
    point), and the assembled trace must be schema-clean with both
    nodes' span lanes under the one trace id."""
    from spacedrive_tpu.fleet import validate_fleet_snapshot

    view = artifact.get("fleet", {})
    problems = validate_fleet_snapshot(view)
    nodes = view.get("nodes", {})
    remote = {n: row for n, row in nodes.items()
              if isinstance(row, dict) and not row.get("local")}
    local = {n: row for n, row in nodes.items()
             if isinstance(row, dict) and row.get("local")}
    if len(remote) != 1 or len(local) != 1:
        problems.append(
            f"fleet: want exactly 1 local + 1 remote row, got "
            f"{len(local)}+{len(remote)}")
        return problems
    (rname, rrow), (_lname, lrow) = \
        next(iter(remote.items())), next(iter(local.items()))
    if not rrow.get("reachable"):
        problems.append(f"fleet: remote row {rname} not reachable: "
                        f"{rrow.get('error')}")
        return problems

    def attributed(row: dict, sub: str, resource: str) -> bool:
        return any(e.get("resource") == resource
                   for e in row.get("attribution", {}).get(sub, []))

    for sub, resource in (("bench", "bench.shed"),
                          ("store", "store.db.write_lock"),
                          ("p2p", "p2p.ping")):
        if not attributed(rrow, sub, resource):
            problems.append(
                f"fleet: seeded {resource} not attributed on the "
                f"REMOTE row {rname}")
        if attributed(lrow, sub, resource):
            problems.append(
                f"fleet: {resource} leaked onto the LOCAL row — "
                "per-node attribution is not separated")
    if rrow.get("states", {}).get("store") != "saturated":
        problems.append("fleet: remote store state not saturated")
    if rrow.get("skew_s") is None:
        problems.append("fleet: remote row carries no skew estimate")

    # The assembled-trace half shares trace_export's fleet gate (lane
    # presence per node pid, skew metadata, no foreign trace ids) —
    # one implementation for both CLIs; this gate only adds the
    # self-check-specific facts.
    from tools.trace_export import fleet_problems

    doc = artifact.get("trace", {})
    problems.extend(fleet_problems(doc))  # includes the schema gate
    other = doc.get("otherData", {}) if isinstance(doc, dict) else {}
    names = other.get("nodes", [])
    if len(names) != 2:
        problems.append(f"trace: want exactly 2 node lanes, "
                        f"got {names}")
    if other.get("trace") != artifact.get("trace_id"):
        # With this pinned, fleet_problems' per-lane span presence +
        # foreign-id rejection together prove both nodes contributed
        # spans under THE seeded trace id.
        problems.append(
            f"trace: assembled for {other.get('trace')!r}, self-check "
            f"seeded {artifact.get('trace_id')!r}")
    return problems


def build_self_check() -> dict:
    """Drive three KNOWN saturations through the real registry and a
    real HealthMonitor, so the artifact exercises every schema shape:
    channel shed, store write-lock wait, and a fired timeout budget."""
    from spacedrive_tpu import channels, health, telemetry
    from spacedrive_tpu.telemetry import (
        STORE_WRITE_LOCK_WAIT_SECONDS,
        TIMEOUTS_FIRED,
    )

    monitor = health.HealthMonitor(interval_s=0.05)
    # 1. a shedding channel (tools-owned bench contract, shed_new)
    ch = channels.channel("bench.shed")
    for i in range(2 * ch.capacity):
        ch.put_nowait(i)
    # 2. a held store write lock's wait histogram
    STORE_WRITE_LOCK_WAIT_SECONDS.observe(0.8)
    # 3. a declared network budget firing
    TIMEOUTS_FIRED.labels(name="p2p.ping").inc()
    time.sleep(0.06)  # a real (if tiny) window for the rates
    snap = monitor.sample()
    del telemetry
    return {
        "metric": "sd_top",
        "source": "self-check",
        "health": snap,
    }


def self_check_problems(artifact: dict) -> list:
    """Schema + semantic gate over the self-check artifact: the three
    induced saturations must be attributed to the right declared
    resources by name."""
    from spacedrive_tpu import health

    snap = artifact.get("health", {})
    problems = health.validate_health_snapshot(snap)
    attribution = snap.get("attribution", {})

    def attributed(sub: str, resource: str) -> bool:
        return any(e.get("resource") == resource
                   for e in attribution.get(sub, []))

    if not attributed("bench", "bench.shed"):
        problems.append(
            "self-check: shedding bench.shed channel not attributed")
    if not attributed("store", "store.db.write_lock"):
        problems.append(
            "self-check: write-lock wait not attributed to "
            "store.db.write_lock")
    if not attributed("p2p", "p2p.ping"):
        problems.append(
            "self-check: fired p2p.ping budget not attributed")
    if snap.get("states", {}).get("store") != "saturated":
        problems.append("self-check: store state not saturated")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live operator top / health-artifact gate")
    ap.add_argument("--url", default="", metavar="http://host:port",
                    help="attach to a live node's rspc host")
    ap.add_argument("--json", action="store_true",
                    help="emit one schema-validated JSON artifact "
                         "(without --url: run the built-in self-check; "
                         "exit 1 on any violation)")
    ap.add_argument("--input", default="", metavar="PATH",
                    help="validate an existing sd_top JSON artifact")
    ap.add_argument("--out", default="", metavar="PATH",
                    help="write the (validated) artifact here")
    ap.add_argument("--once", action="store_true",
                    help="render one frame instead of polling")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll seconds in live mode (default 2)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: render/validate the merged "
                         "per-(node, subsystem) view from "
                         "fleet.health (without --url, --json runs "
                         "the 2-node self-check)")
    args = ap.parse_args(argv)

    from spacedrive_tpu import health

    if args.input:
        try:
            with open(args.input, encoding="utf-8") as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"sd_top: unreadable {args.input}: {e}",
                  file=sys.stderr)
            return 1
        if args.fleet or artifact.get("metric") == "sd_top_fleet":
            from spacedrive_tpu.fleet import validate_fleet_snapshot

            problems = validate_fleet_snapshot(
                artifact.get("fleet", artifact))
        else:
            problems = health.validate_health_snapshot(
                artifact.get("health", artifact))
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"sd_top: valid ({args.input})")
        return 0

    if args.json and not args.url:
        if args.fleet:
            artifact = build_fleet_self_check()
            problems = fleet_self_check_problems(artifact)
        else:
            artifact = build_self_check()
            problems = self_check_problems(artifact)
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            print(f"sd_top: {len(problems)} violation(s)",
                  file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=1)
            print(f"sd_top: wrote {args.out}", file=sys.stderr)
        print(json.dumps(artifact))
        return 0

    if not args.url:
        ap.error("--url is required outside --json/--input modes")

    if args.fleet:
        from spacedrive_tpu.fleet import validate_fleet_snapshot

        while True:
            view = fetch_fleet(args.url)
            problems = validate_fleet_snapshot(view)
            for p in problems:
                print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
            if problems:
                return 1
            if args.json:
                artifact = {"metric": "sd_top_fleet",
                            "source": args.url, "fleet": view}
                if args.out:
                    with open(args.out, "w", encoding="utf-8") as f:
                        json.dump(artifact, f, indent=1)
                print(json.dumps(artifact))
                return 0
            frame = render_fleet(view, source=args.url)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))

    while True:
        snap = fetch_health(args.url)
        problems = health.validate_health_snapshot(snap)
        for p in problems:
            print(f"sd_top: SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        if args.json:
            artifact = {"metric": "sd_top", "source": args.url,
                        "health": snap}
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    json.dump(artifact, f, indent=1)
            print(json.dumps(artifact))
            return 0
        try:
            metrics = fetch_metrics(args.url)
        except Exception:
            metrics = None  # health alone still renders
        frame = render_top(snap, source=args.url, metrics=metrics)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    sys.exit(main())
