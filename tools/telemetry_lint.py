"""Static telemetry-namespace lint — COMPATIBILITY SHIM.

The implementation moved into the sdlint framework
(`tools/sdlint/passes/telemetry.py`) when the PR 3 one-off lint was
folded in as sdlint's fifth pass; this module keeps the original CLI
(`python tools/telemetry_lint.py [package_dir]`) and the
`run_lint(package_dir) -> [problem, ...]` API that
tests/test_telemetry.py and any local tooling already use.

Rules (unchanged): metric families register only in
spacedrive_tpu/telemetry.py, under string-literal, collision-free
names following `sd_<layer>_<what>`. Prefer `python -m tools.sdlint`
(optionally `--passes telemetry`) for new workflows — it adds the
baseline machinery and the other four invariant passes.
"""

from __future__ import annotations

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
if os.path.dirname(_HERE) not in sys.path:
    sys.path.insert(0, os.path.dirname(_HERE))

from tools.sdlint.passes.telemetry import (  # noqa: E402,F401
    CENTRAL_MODULE,
    CLASS_NAMES,
    FACTORY_NAMES,
    NAME_RE,
    lint_source,
    run_lint,
)


def main(argv) -> int:
    pkg = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(_HERE), "spacedrive_tpu")
    problems = run_lint(pkg)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"telemetry lint: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print("telemetry lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
