"""Depth-N overlap-pipeline microbench: depth sweep × link speeds.

The pipeline analog of tools/chan_bench.py: drives ops/overlap.py's
depth-N ring through a sweep of pipeline depths and (simulated) H2D
link rates and emits a BENCH-style JSON artifact — measured files/s vs
the computed max(stage, h2d, kernel) steady-state bound, the stall
breakdown (stage/retire/calibration seconds), depth high-water, and
the per-device batch split — so a pipeline regression gates like a
perf regression instead of surfacing as a mystery e2e dip.

Simulated links (`--links`, GB/s) use SDTPU_SIM_LINK_GBPS: each H2D
additionally sleeps nbytes/rate per device stream, deterministically,
so the sweep runs identically on a CPU container and a TPU host; pass
``--links real`` to measure the actual link instead.

    python -m tools.overlap_bench --json /tmp/overlap.json
    python -m tools.overlap_bench --depths 1,2,4 --links 0.05,0.5
    python -m tools.overlap_bench --gate   # exit 1 when depth>=3 misses
                                           # its bound by more than 1.3x

The default kernel is the real device BLAKE3 body; `--cheap-kernel`
swaps in a trivially-compiling checksum so CI sweeps don't pay the
~45 s BLAKE3 compile per program variant (the overlap math being
measured is kernel-agnostic).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BOUND_TOLERANCE = 1.3  # acceptance: measured >= bound / 1.3 at depth >= 3


def _cheap_kernel(words, lengths):
    """Trivially-compiling [B, 8] checksum stand-in for the BLAKE3 body
    (module-level def so _jitted caches one program per donate flag)."""
    import jax.numpy as jnp

    s = words.sum(axis=(1, 2)).astype(jnp.uint32)
    return s[:, None] + jnp.arange(8, dtype=jnp.uint32)[None, :]


def run_sweep(depths, links, batch=32, batches=8, file_size=120_000,
              cheap_kernel=False, donate=None, calibrate_every=None,
              stagings=("env",)):
    """calibrate_every: None keeps run_overlapped's interleaved mid-run
    cadence (real links — the bound must come from the same weather
    window as the measurement); >= batches disables mid-run pauses
    (simulated links are deterministic, so re-sampling buys nothing
    and each pause's drain+refill denies short deep-pipeline runs
    their steady state).

    stagings: staging-backend axis per (link, depth) row — 'native' /
    'python' pin SDTPU_STAGE_NATIVE for that row (same run, same
    corpus: the A/B the BENCH artifact commits), 'env' leaves the
    caller's flag alone. Each row records the requested axis value AND
    the backend that actually fed it (a 'native' request degrades to
    python when libsdio.so is absent — the artifact must say so),
    plus the flight recorder's per-batch bound-attribution histogram
    (which of stage/h2d/kernel bound each retired window) so a
    staging-bound pipeline is visible as data, not inference."""
    from spacedrive_tpu.ops import overlap
    from spacedrive_tpu import flight

    kernel = _cheap_kernel if cheap_kernel else None
    rows = []
    root = tempfile.mkdtemp(prefix="sdtpu-overlap-bench-")
    try:
        corpus = overlap.make_sparse_corpus(
            root, batch * batches, file_size, batch)
        from spacedrive_tpu import flags as _flags

        prior = _flags.raw("SDTPU_SIM_LINK_GBPS")
        prior_stage = _flags.raw("SDTPU_STAGE_NATIVE")
        for link in links:
            if link == "real":
                os.environ.pop("SDTPU_SIM_LINK_GBPS", None)
            else:
                os.environ["SDTPU_SIM_LINK_GBPS"] = str(link)
            try:
                for depth, staging in ((d, s) for d in depths
                                       for s in stagings):
                    if staging == "native":
                        os.environ["SDTPU_STAGE_NATIVE"] = "on"
                    elif staging == "python":
                        os.environ["SDTPU_STAGE_NATIVE"] = "off"
                    mark = len(flight.RECORDER.snapshot())
                    try:
                        _res, stats = overlap.run_overlapped(
                            corpus, kernel=kernel, depth=depth,
                            donate=donate,
                            calibrate_every=calibrate_every)
                    finally:
                        if staging != "env":
                            if prior_stage is None:
                                os.environ.pop("SDTPU_STAGE_NATIVE",
                                               None)
                            else:
                                os.environ["SDTPU_STAGE_NATIVE"] = \
                                    prior_stage
                    report = stats.bound_report()
                    attribution = {}
                    for ev in flight.RECORDER.snapshot()[mark:]:
                        if ev.get("lane") == "window":
                            b = ev["binding"]
                            attribution[b] = attribution.get(b, 0) + 1
                    rows.append({
                        "depth": depth,
                        "link_gbps": link,
                        "staging": staging,
                        "staging_backend": stats.staging_backend,
                        "devices": stats.n_devices,
                        "donated": stats.donate,
                        "measured_files_per_sec":
                            report["measured_files_per_sec"],
                        "bound_files_per_sec":
                            report["bound_files_per_sec"],
                        "ratio": report["ratio"],
                        "depth_high_water": stats.depth_high_water,
                        "per_device_batches": stats.per_device_batches,
                        "donated_reuse": stats.donated_reuse,
                        "h2d_bytes": stats.h2d_bytes,
                        "h2d_s": round(stats.h2d_s, 4),
                        "stall_s": {
                            "stage": round(stats.stage_s, 4),
                            "retire": round(stats.retire_stall_s, 4),
                            "calibration": round(stats.calibration_s, 4),
                        },
                        "components_s": {
                            "stage": round(stats.t_stage_1, 4),
                            "h2d": round(stats.t_h2d_1, 4),
                            "kernel_fetch": round(stats.t_kernel_1, 4),
                        },
                        "bound_attribution": attribution,
                        "calibrations": report["calibrations"],
                        "bound_reason": report["reason"],
                    })
            finally:
                # Restore the CALLER's sim-link setting (an operator
                # running the sweep with the flag exported keeps it),
                # not just unset it.
                if prior is None:
                    os.environ.pop("SDTPU_SIM_LINK_GBPS", None)
                else:
                    os.environ["SDTPU_SIM_LINK_GBPS"] = prior
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return rows


def gate_failures(rows):
    """Rows violating the acceptance shape: at depth >= 3 the measured
    rate must land within BOUND_TOLERANCE of its same-run bound AND
    strictly beat the same link's depth-1 run."""
    by_link = {}
    for r in rows:
        key = (r["link_gbps"], r.get("staging", "env"))
        by_link.setdefault(key, {})[r["depth"]] = r
    bad = []
    for link, by_depth in by_link.items():
        base = by_depth.get(1)
        for depth, r in by_depth.items():
            if depth < 3:
                continue
            if r["bound_files_per_sec"] and \
                    r["measured_files_per_sec"] * BOUND_TOLERANCE \
                    < r["bound_files_per_sec"]:
                bad.append((link, depth, "missed bound", r["ratio"]))
            if base is not None and r["measured_files_per_sec"] \
                    <= base["measured_files_per_sec"]:
                bad.append((link, depth, "not better than depth 1",
                            r["measured_files_per_sec"]))
    return bad


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--depths", default="1,2,4",
                    help="comma-separated pipeline depths to sweep")
    ap.add_argument("--links", default="0.05,0.5",
                    help="comma-separated simulated link GB/s "
                         "(or 'real' for the actual link)")
    ap.add_argument("--batch", type=int, default=32,
                    help="files per batch (32 reuses the tier-1 "
                         "compile cache)")
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--file-size", type=int, default=120_000)
    ap.add_argument("--cheap-kernel", action="store_true",
                    help="trivially-compiling checksum kernel (CI)")
    ap.add_argument("--donate", choices=("on", "off"), default=None,
                    help="override SDTPU_DONATE_BUFFERS for the sweep")
    ap.add_argument("--staging", default="env",
                    help="comma-separated staging backends to A/B per "
                         "depth row: python, native (pins "
                         "SDTPU_STAGE_NATIVE per row), or env "
                         "(default: the caller's flag)")
    ap.add_argument("--calibrate-every", type=int, default=None,
                    metavar="N",
                    help="mid-run calibration cadence in batches "
                         "(default: run_overlapped's interleaved "
                         "cadence; pass >= --batches to disable "
                         "mid-run pauses on deterministic simulated "
                         "links)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when a depth>=3 row misses its bound "
                         f"by more than {BOUND_TOLERANCE}x or fails to "
                         "beat depth 1")
    ap.add_argument("--json", default="", metavar="PATH",
                    help="write the sweep as one BENCH-style artifact")
    ap.add_argument("--trace", default="", metavar="PATH",
                    help="export the sweep's flight-recorder timeline "
                         "+ span ring as a schema-validated Chrome-"
                         "trace JSON next to the artifact (exit 1 on "
                         "schema violation)")
    args = ap.parse_args()

    depths = [int(d) for d in args.depths.split(",") if d.strip()]
    links = [l if l == "real" else float(l)
             for l in args.links.split(",") if l.strip()]
    donate = None if args.donate is None else args.donate == "on"
    stagings = [s.strip() for s in args.staging.split(",") if s.strip()]
    for s in stagings:
        if s not in ("python", "native", "env"):
            ap.error(f"--staging: unknown backend {s!r}")

    if args.trace:
        # The trace artifact should cover THIS sweep only.
        from spacedrive_tpu import flight

        flight.RECORDER.clear()

    # Whole-sweep health window (spacedrive_tpu/health.py): cursors
    # established before the sweep, sampled once after — the artifact
    # carries WHAT saturated (pipeline stall split, channel behavior)
    # next to the measured/bound rows it explains.
    from spacedrive_tpu.health import (
        HealthMonitor,
        validate_health_snapshot,
    )

    monitor = HealthMonitor()
    # A loose in-memory black box for the sweep (no store directory:
    # the index carries the bundles inline): anything that saturates
    # mid-sweep freezes a bundle whose header rides the artifact.
    from spacedrive_tpu import incidents as _incidents

    own_obs = _incidents.current() is None
    obs = _incidents.install(monitor=monitor, node_id="overlap-bench",
                             node_name="overlap-bench")
    rows = run_sweep(depths, links, batch=args.batch,
                     batches=args.batches, file_size=args.file_size,
                     cheap_kernel=args.cheap_kernel, donate=donate,
                     calibrate_every=args.calibrate_every,
                     stagings=stagings)
    hsnap = monitor.sample()
    health_problems = validate_health_snapshot(hsnap)
    for p in health_problems:
        print(f"HEALTH SCHEMA: {p}", file=sys.stderr)
    artifact = {
        "metric": "overlap_bench",
        "unit": "files/s",
        "bound_tolerance": BOUND_TOLERANCE,
        "batch": args.batch, "batches": args.batches,
        "file_size": args.file_size,
        "cheap_kernel": bool(args.cheap_kernel),
        "sweep": rows,
        "health": {
            "window_s": hsnap["window_s"],
            "states": hsnap["states"],
            "attribution": hsnap["attribution"],
        },
        "incidents": {
            "enabled": obs is not None,
            "headers": obs.list() if obs is not None else [],
            "deduped": obs.deduped() if obs is not None else {},
        },
    }
    if own_obs and obs is not None:
        # This sweep installed the process-global observatory; detach
        # it so an embedding caller's later install starts clean.
        _incidents.uninstall()
    print(json.dumps(artifact))
    if args.json:
        from spacedrive_tpu import persist

        persist.atomic_write("bench.artifact", args.json,
                             json.dumps(artifact, indent=1))
    if args.trace:
        from spacedrive_tpu import flight

        problems = flight.write_trace_artifact(args.trace,
                                               "overlap_bench")
        for p in problems:
            print(f"TRACE SCHEMA: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"trace artifact: {args.trace}", file=sys.stderr)
    if health_problems:
        return 1
    if args.gate:
        bad = gate_failures(rows)
        # Same discipline as load_bench's gate: a frozen bundle is
        # fine (the sweep may genuinely saturate), but one whose
        # trigger names nothing declared lost its cause.
        from tools.load_bench import _declared_resource

        for h in artifact["incidents"]["headers"]:
            trig = h.get("trigger") or {}
            if not _declared_resource(trig.get("resource", "")):
                bad.append((trig.get("kind"), "-",
                            "unattributed incident",
                            trig.get("resource")))
        for link, depth, why, val in bad:
            print(f"GATE: link={link} depth={depth}: {why} ({val})",
                  file=sys.stderr)
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
