"""sdlint — multi-pass concurrency & invariant analyzer for spacedrive_tpu.

An AST + call-graph static-analysis framework over `spacedrive_tpu/`
and `tools/`, checking the invariant families the engine hand-enforces
(in the compile-time-checkable spirit of RacerD, Blackshear et al.,
OOPSLA 2018):

- blocking-async   — blocking calls (sqlite, file IO, time.sleep,
                     subprocess, native encoders, future waits)
                     reachable from `async def` without
                     asyncio.to_thread/executor wrapping, via an
                     interprocedural reachability walk
- lock-discipline  — awaits/blocking waits while a threading lock is
                     held, nested write-transaction entry inside an
                     open transaction, and lock-order cycles over the
                     project-wide lock graph (the PR 1 store/db.py
                     reader-registration deadlock shape)
- crdt-parity      — transactions writing SHARED/RELATION model tables
                     without emitting a sync op in the same scope
- flag-registry    — every SDTPU_* literal declared in
                     spacedrive_tpu/flags.py; no direct environ reads
                     of SDTPU flags outside the registry
- telemetry        — the PR 3 metric-namespace lint, folded in
                     (tools/telemetry_lint.py remains as a CLI shim)

Run `python -m tools.sdlint --help`. Findings ship as human text or
JSON; known findings live in `tools/sdlint/baseline.json`, which may
only shrink (see baseline.py). The runtime twin of this tool is
`spacedrive_tpu/sanitize.py` (SDTPU_SANITIZE=1).
"""

from .core import Finding, Project, load_project, run_passes  # noqa: F401
from .baseline import Baseline  # noqa: F401
