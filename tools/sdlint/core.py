"""sdlint core: source index, function table, call graph, findings.

Everything here is pure-stdlib AST work. The call graph is a
best-effort static over-approximation with three resolution tiers
(documented at `ProjectIndex.resolve`): same-class methods, same-module
functions, then project-unique names. Passes receive a `Project` and
return `Finding`s; unresolvable dynamic dispatch (router handler
tables, callbacks) is out of scope by design — the runtime sanitizer
(spacedrive_tpu/sanitize.py) covers that half.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterable, List, Optional, Sequence,
                    Set)

SUPPRESS_RE = re.compile(r"#\s*sdlint:\s*ok\[([a-z0-9_,-]+)\]")


@dataclass(frozen=True)
class Finding:
    """One problem. `key()` is the stable baseline identity: it omits
    line numbers so unrelated edits above a known finding do not churn
    the baseline file."""

    pass_name: str           # e.g. "blocking-async"
    code: str                # short rule id within the pass
    path: str                # repo-relative posix path
    qual: str                # enclosing function qualname ("" = module)
    ident: str               # stable detail (root call, lock pair, ...)
    message: str             # human sentence
    lineno: int

    def key(self) -> str:
        return "::".join(
            (self.pass_name, self.code, self.path, self.qual, self.ident))

    def text(self) -> str:
        where = f"{self.path}:{self.lineno}"
        q = f" [{self.qual}]" if self.qual else ""
        return f"{where}: ({self.pass_name}/{self.code}){q} {self.message}"

    def as_json(self) -> dict:
        return {
            "pass": self.pass_name, "code": self.code, "path": self.path,
            "qual": self.qual, "ident": self.ident,
            "message": self.message, "line": self.lineno,
            "key": self.key(),
        }


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class CallSite:
    node: ast.Call
    name: str                 # dotted callee ("self.foo", "mod.f", "f")
    wrapped: bool             # appears inside a to_thread/executor arg


@dataclass
class FuncInfo:
    src: "SourceFile"
    qual: str                 # "Class.method" | "func" | "outer.inner"
    cls: Optional[str]        # enclosing class name, if a method
    node: ast.AST             # FunctionDef | AsyncFunctionDef
    is_async: bool
    calls: List[CallSite] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


# Calls whose ARGUMENTS are function references executed off-loop —
# anything passed into them is not executed on the caller's thread.
# call_threadsafe is threadctx.py's hardened call_soon_threadsafe;
# run_coroutine_threadsafe is its coroutine sibling.
_THREAD_WRAPPERS = {"to_thread", "run_in_executor", "submit",
                    "call_soon_threadsafe", "call_threadsafe",
                    "run_coroutine_threadsafe"}


class SourceFile:
    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=relpath)
        # line numbers carrying an `# sdlint: ok[...]` suppression,
        # mapped to the pass names they waive.
        self.suppressions: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = {
                    p.strip() for p in m.group(1).split(",") if p.strip()}

    def suppressed(self, pass_name: str, lineno: int) -> bool:
        """A finding is waived by a marker on its line or the line
        above (the comment-above idiom)."""
        for ln in (lineno, lineno - 1):
            waived = self.suppressions.get(ln)
            if waived and (pass_name in waived or "all" in waived):
                return True
        return False


class _FuncCollector(ast.NodeVisitor):
    def __init__(self, src: SourceFile, out: List[FuncInfo]):
        self.src = src
        self.out = out
        self._stack: List[str] = []
        self._cls: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls.append(node.name)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()
        self._cls.pop()

    def _func(self, node, is_async: bool):
        qual = ".".join(self._stack + [node.name])
        info = FuncInfo(self.src, qual,
                        self._cls[-1] if self._cls else None,
                        node, is_async)
        _collect_calls(node, info)
        self.out.append(info)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._func(node, is_async=True)


def own_body_walk(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's OWN statements: nested function/lambda bodies
    are skipped (their code does not run when this function runs), but
    the nested nodes themselves are yielded so callers can see the
    boundary if they care."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _collect_calls(fn_node: ast.AST, info: FuncInfo) -> None:
    wrapped_args: Set[int] = set()
    for node in own_body_walk(fn_node):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is not None and d.rsplit(".", 1)[-1] in _THREAD_WRAPPERS:
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        wrapped_args.add(id(sub))
    for node in own_body_walk(fn_node):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None:
                continue
            info.calls.append(
                CallSite(node, d, wrapped=id(node) in wrapped_args))


# Attribute names too ubiquitous across the stdlib/ecosystem to resolve
# by name alone: `task.cancel()` must not resolve to JobManager.cancel,
# `conn.close()` not to Database.close. Methods on self/cls still
# resolve; these only gate the name-based fallback tiers.
_COMMON_ATTRS = {
    "cancel", "close", "stop", "start", "run", "get", "put", "set",
    "send", "recv", "read", "write", "update", "create", "delete",
    "insert", "append", "pop", "clear", "add", "remove", "discard",
    "join", "result", "done", "wait", "acquire", "release", "open",
    "items", "keys", "values", "submit", "flush", "commit", "rollback",
    "execute", "encode", "decode", "emit", "copy", "next", "save",
    "load", "name",
}


class ProjectIndex:
    """Function table + the three-tier call resolver."""

    def __init__(self, files: Sequence[SourceFile]):
        self.funcs: List[FuncInfo] = []
        for src in files:
            _FuncCollector(src, self.funcs).visit(src.tree)
        self.by_key: Dict[str, FuncInfo] = {
            f"{f.src.relpath}::{f.qual}": f for f in self.funcs}
        self._by_name: Dict[str, List[FuncInfo]] = {}
        for f in self.funcs:
            self._by_name.setdefault(f.name, []).append(f)

    def resolve(self, caller: FuncInfo, name: str) -> Optional[FuncInfo]:
        """Resolve a dotted call target to a project function.

        Tiers: `self.m`/`cls.m` → method m on the caller's class;
        bare `f` → function f in the caller's module; otherwise the
        terminal name, if exactly ONE project function bears it AND the
        name is project-specific (ubiquitous attribute names like
        `close`/`cancel` never resolve through the fallback — the
        receiver is usually a stdlib object). Anything else (stdlib,
        dynamic dispatch) resolves to None.
        """
        parts = name.split(".")
        last = parts[-1]
        if parts[0] in ("self", "cls") and len(parts) == 2 and caller.cls:
            hit = self.by_key.get(
                f"{caller.src.relpath}::{caller.cls}.{last}")
            if hit is not None:
                return hit
        if len(parts) == 1:
            hit = self.by_key.get(f"{caller.src.relpath}::{last}")
            if hit is not None:
                return hit
            # Closures addressable from THIS lexical scope: the
            # caller's own nested functions (`handler.work` from
            # handler) and siblings up the enclosing-scope chain
            # (`_files._spawn_fs_job` from `_files.files_delete`) —
            # probe every ancestor prefix, innermost first.
            scope = caller.qual.split(".")
            for i in range(len(scope), 0, -1):
                hit = self.by_key.get(
                    f"{caller.src.relpath}::"
                    f"{'.'.join(scope[:i])}.{last}")
                if hit is not None:
                    return hit
        if len(parts) > 1 and last in _COMMON_ATTRS:
            return None
        # Other scopes' nested closures are not addressable by name:
        # a bare `partial(...)` must never resolve to some module's
        # `_ingest_answers.partial` inner function. Only top-level
        # functions and direct methods participate in the name-based
        # fallback tiers.
        cands = [c for c in self._by_name.get(last, [])
                 if (c.cls is not None and c.qual == f"{c.cls}.{c.name}")
                 or (c.cls is None and "." not in c.qual)]
        if len(cands) == 1:
            return cands[0]
        same_mod = [c for c in cands if c.src is caller.src]
        if len(same_mod) == 1:
            return same_mod[0]
        return None


class Project:
    def __init__(self, root: str, files: Sequence[SourceFile],
                 problems: Optional[List[str]] = None):
        self.root = root
        self.files = list(files)
        self.index = ProjectIndex(self.files)
        # unparseable-file notes (reported as findings by run_passes)
        self.problems = list(problems or [])


DEFAULT_SCOPES = ("spacedrive_tpu", "tools")
EXCLUDE_DIRS = {"__pycache__"}
# The linter does not lint itself: its pass sources are full of the
# very literals (SDTPU_, metric factories, lock names) it hunts.
EXCLUDE_PREFIXES = ("tools/sdlint/",)


def iter_source_paths(root: str,
                      scopes: Sequence[str] = DEFAULT_SCOPES
                      ) -> List[str]:
    out: List[str] = []
    for scope in scopes:
        base = os.path.join(root, scope)
        if os.path.isfile(base) and base.endswith(".py"):
            out.append(base)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in EXCLUDE_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    rels = []
    for p in sorted(out):
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        if not rel.startswith(EXCLUDE_PREFIXES):
            rels.append(p)
    return rels


def load_project(root: str,
                 paths: Optional[Sequence[str]] = None) -> Project:
    """Project over `paths` (absolute), default: the repo lint scope
    (spacedrive_tpu/ + tools/, minus sdlint itself)."""
    if paths is None:
        paths = iter_source_paths(root)
    files: List[SourceFile] = []
    problems: List[str] = []
    for p in paths:
        rel = os.path.relpath(p, root)
        try:
            files.append(SourceFile(p, rel))
        except SyntaxError as e:
            problems.append(f"{rel}: unparseable: {e}")
    return Project(root, files, problems)


def run_passes(project: Project,
               passes: Optional[Sequence] = None) -> List[Finding]:
    """Run passes (default: all registered) and return suppression-
    filtered findings, sorted by (path, line)."""
    from .passes import all_passes

    if passes is None:
        passes = all_passes()
    findings: List[Finding] = []
    for prob in project.problems:
        path = prob.split(":", 1)[0]
        findings.append(Finding(
            "core", "unparseable", path, "", "syntax", prob, 0))
    src_by_rel = {f.relpath: f for f in project.files}
    for p in passes:
        for f in p.run(project):
            src = src_by_rel.get(f.path)
            if src is not None and src.suppressed(f.pass_name, f.lineno):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.lineno, f.key()))
    return findings


def reverse_closure_files(project: Project,
                          changed: Iterable[str]) -> Set[str]:
    """The incremental-lint scope: `changed` relpaths plus every file
    whose functions (transitively) CALL into them — the reverse
    call-graph closure over resolvable edges. A change to a callee can
    invalidate any caller-side invariant (lock order, context
    reachability, blocking closure), so callers re-lint; callees of
    changed files keep their own previously-clean verdict."""
    idx = project.index
    rev: Dict[str, Set[str]] = {}
    for fn in idx.funcs:
        for site in fn.calls:
            callee = idx.resolve(fn, site.name)
            if callee is not None and \
                    callee.src.relpath != fn.src.relpath:
                rev.setdefault(callee.src.relpath,
                               set()).add(fn.src.relpath)
    known = {f.relpath for f in project.files}
    closure = {c for c in changed if c in known}
    frontier = list(closure)
    while frontier:
        f = frontier.pop()
        for caller in rev.get(f, ()):
            if caller not in closure:
                closure.add(caller)
                frontier.append(caller)
    return closure


def git_changed_paths(root: str, ref: str = "HEAD") -> List[str]:
    """Repo-relative posix paths touched vs `ref` (worktree + index)
    plus untracked files — the pre-commit view. Raises on git errors
    (missing ref, not a repo) so the CLI can report them."""
    import subprocess

    def run(*args: str) -> List[str]:
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)}: {proc.stderr.strip()}")
        return [ln.strip() for ln in proc.stdout.splitlines()
                if ln.strip()]

    out = set(run("diff", "--name-only", ref, "--"))
    out.update(run("ls-files", "--others", "--exclude-standard"))
    return sorted(p.replace(os.sep, "/") for p in out)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
