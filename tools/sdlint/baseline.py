"""Baseline (suppression) file — allowed to SHRINK, never to grow.

`tools/sdlint/baseline.json` records the finding keys that were
present when a pass first landed and were judged acceptable (with a
one-line reason each). Policy, enforced by tests/test_sdlint.py:

- every current finding must be in the baseline (or the build fails);
- the checked-in `budget` is an upper bound on baseline size; adding
  an entry without raising the budget fails the build, and raising the
  budget is a human, review-visible act;
- `--update-baseline` only PRUNES entries whose finding no longer
  exists and lowers the budget to the new size — it cannot add.

Fixing a finding therefore shrinks the file on the next
`--update-baseline`; introducing one makes CI red until the code is
fixed (or a reviewer deliberately grows the baseline by hand).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from .core import Finding

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


class Baseline:
    def __init__(self, entries: Dict[str, str], budget: int):
        self.entries = dict(entries)     # finding key → reason
        self.budget = budget

    @classmethod
    def load(cls, path: str = DEFAULT_PATH) -> "Baseline":
        if not os.path.exists(path):
            return cls({}, 0)
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        return cls(raw.get("findings", {}), int(raw.get("budget", 0)))

    def save(self, path: str = DEFAULT_PATH) -> None:
        raw = {
            "_policy": (
                "Shrink-only. New findings must be FIXED, not "
                "baselined; --update-baseline prunes stale entries and "
                "lowers the budget, never adds. See baseline.py."),
            "budget": self.budget,
            "findings": {k: self.entries[k] for k in sorted(self.entries)},
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(raw, f, indent=2)
            f.write("\n")

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """(new, baselined, stale_keys) for a findings set."""
        current = {f.key() for f in findings}
        new = [f for f in findings if f.key() not in self.entries]
        old = [f for f in findings if f.key() in self.entries]
        stale = sorted(k for k in self.entries if k not in current)
        return new, old, stale

    def over_budget(self) -> bool:
        return len(self.entries) > self.budget

    def prune(self, findings: Sequence[Finding]) -> List[str]:
        """Drop stale entries, lower the budget. Returns dropped keys."""
        _new, _old, stale = self.split(findings)
        for k in stale:
            del self.entries[k]
        self.budget = min(self.budget, len(self.entries)) \
            if self.budget else len(self.entries)
        return stale
