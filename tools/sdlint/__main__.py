"""sdlint CLI.

    python -m tools.sdlint                     # lint the tree, text out
    python -m tools.sdlint --json              # machine-readable findings
    python -m tools.sdlint --passes lock-discipline,crdt-parity
    python -m tools.sdlint --passes            # list registered passes
    python -m tools.sdlint --update-baseline   # prune stale entries only
    python -m tools.sdlint --write-baseline    # bootstrap (see policy!)
    python -m tools.sdlint --flag-table        # README flag table stdout
    python -m tools.sdlint --timeout-table     # README timeout table
    python -m tools.sdlint --chan-table        # README channel table
    python -m tools.sdlint --stats             # per-pass counts + wall-time

Exit status: 0 when every finding is baselined (or none), 1 otherwise.
The baseline may only shrink — see tools/sdlint/baseline.py.
"""

from __future__ import annotations

import argparse
import json
import sys

from .baseline import DEFAULT_PATH, Baseline
from .core import load_project, repo_root, run_passes
from .passes import get_passes


def stats(root=None):
    """[(pass_name, finding_count, seconds)] over the whole tree,
    with 'index' (project load) and 'total' rows — the `--stats` view,
    and the hook tests/test_sdlint.py pins the <30s analyzer budget
    on so pass growth can't silently blow up tier-1."""
    import time

    from .passes import all_passes

    root = root or repo_root()
    out = []
    t0 = time.perf_counter()
    project = load_project(root)
    out.append(("index", len(project.files), time.perf_counter() - t0))
    for p in all_passes():
        t1 = time.perf_counter()
        found = run_passes(project, [p])
        out.append((p.name, len(found), time.perf_counter() - t1))
    out.append(("total", sum(c for n, c, _ in out if n != "index"),
                time.perf_counter() - t0))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.sdlint",
        description="spacedrive_tpu concurrency & invariant analyzer")
    ap.add_argument("--root", default=repo_root(),
                    help="repo root (default: auto)")
    ap.add_argument("--passes", nargs="?", const="?list", default="",
                    help="comma-separated subset of passes; with no "
                         "value, list the registered passes and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=DEFAULT_PATH,
                    help="baseline file path")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="prune stale baseline entries + lower budget "
                         "(never adds)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="bootstrap: write ALL current findings as the "
                         "baseline (policy: one-time, review-visible)")
    ap.add_argument("--flag-table", action="store_true",
                    help="print the generated README flag table and exit")
    ap.add_argument("--timeout-table", action="store_true",
                    help="print the generated README timeout table "
                         "and exit")
    ap.add_argument("--chan-table", action="store_true",
                    help="print the generated README channel table "
                         "and exit")
    ap.add_argument("--stats", action="store_true",
                    help="per-pass finding counts and wall-time "
                         "(informational; exit 0)")
    args = ap.parse_args(argv)

    if args.no_baseline and (args.update_baseline or args.write_baseline):
        ap.error("--no-baseline cannot be combined with "
                 "--update-baseline/--write-baseline (it would rewrite "
                 "the baseline from an empty view)")

    if args.flag_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import flags
        print(flags.flag_table_markdown())
        return 0

    if args.timeout_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import timeouts
        print(timeouts.timeout_table_markdown())
        return 0

    if args.chan_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import channels
        print(channels.chan_table_markdown())
        return 0

    if args.stats:
        for name, count, secs in stats(args.root):
            print(f"{name:22s} {count:4d} finding(s) {secs:7.2f}s")
        return 0

    if args.passes == "?list":
        from .passes import PASSES
        for name in PASSES:
            print(name)
        return 0

    pass_names = [p.strip() for p in args.passes.split(",") if p.strip()]
    passes = get_passes(pass_names or None)
    project = load_project(args.root)
    findings = run_passes(project, passes)
    # A subset run must not judge (or prune!) other passes' baseline
    # entries: out-of-scope keys are carved out and merged back on save.
    out_of_scope = {}

    if args.write_baseline:
        bl = Baseline({f.key(): f.message for f in findings},
                      budget=len({f.key() for f in findings}))
        bl.save(args.baseline)
        print(f"baseline written: {len(bl.entries)} entr(y/ies), "
              f"budget {bl.budget}")
        return 0

    bl = Baseline({}, 0) if args.no_baseline else Baseline.load(args.baseline)
    if pass_names:
        ran = set(pass_names) | {"core"}
        out_of_scope = {k: v for k, v in bl.entries.items()
                        if k.split("::", 1)[0] not in ran}
        bl.entries = {k: v for k, v in bl.entries.items()
                      if k not in out_of_scope}
    new, baselined, stale = bl.split(findings)

    if args.update_baseline:
        dropped = bl.prune(findings)
        bl.entries.update(out_of_scope)
        bl.budget += len(out_of_scope)
        bl.save(args.baseline)
        print(f"baseline: dropped {len(dropped)} stale entr(y/ies), "
              f"{len(bl.entries)} remain, budget {bl.budget}")

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_json() for f in new],
            "baselined": [f.as_json() for f in baselined],
            "stale_baseline_keys": stale,
            "budget": bl.budget,
        }, indent=2))
    else:
        for f in new:
            print(f.text())
        if stale and not args.update_baseline:
            print(f"note: {len(stale)} stale baseline entr(y/ies) — run "
                  f"--update-baseline to shrink the file",
                  file=sys.stderr)
        print(f"sdlint: {len(new)} new finding(s), "
              f"{len(baselined)} baselined, {len(stale)} stale")
    if bl.over_budget():
        print("sdlint: baseline exceeds its budget — entries were added "
              "by hand without raising the budget (see baseline.py "
              "policy)", file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
