"""sdlint CLI.

    python -m tools.sdlint                     # lint the tree, text out
    python -m tools.sdlint --json              # machine-readable findings
    python -m tools.sdlint --passes lock-discipline,crdt-parity
    python -m tools.sdlint --passes            # list registered passes
    python -m tools.sdlint --changed           # files touched vs HEAD +
                                               # reverse-call closure
    python -m tools.sdlint --changed origin/main
    python -m tools.sdlint --update-baseline   # prune stale entries only
    python -m tools.sdlint --write-baseline    # bootstrap (see policy!)
    python -m tools.sdlint --flag-table        # README flag table stdout
    python -m tools.sdlint --timeout-table     # README timeout table
    python -m tools.sdlint --chan-table        # README channel table
    python -m tools.sdlint --sql-table         # README statement table
    python -m tools.sdlint --wire-table        # README wire-message table
    python -m tools.sdlint --write-wire-baseline  # regen wire snapshot
    python -m tools.sdlint --stats             # per-pass counts + wall-time

Exit status: 0 when every finding is baselined (or none), 1 otherwise.
The baseline may only shrink — see tools/sdlint/baseline.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import DEFAULT_PATH, Baseline
from .core import (
    DEFAULT_SCOPES,
    EXCLUDE_PREFIXES,
    Project,
    git_changed_paths,
    load_project,
    repo_root,
    reverse_closure_files,
    run_passes,
)
from .passes import get_passes


def stats(root=None):
    """[(pass_name, finding_count, seconds)] over the whole tree,
    with 'index' (project load) and 'total' rows — the `--stats` view,
    and the hook tests/test_sdlint.py pins the <30s analyzer budget
    on so pass growth can't silently blow up tier-1."""
    import time

    from .passes import all_passes

    root = root or repo_root()
    out = []
    t0 = time.perf_counter()
    project = load_project(root)
    out.append(("index", len(project.files), time.perf_counter() - t0))
    for p in all_passes():
        t1 = time.perf_counter()
        found = run_passes(project, [p])
        out.append((p.name, len(found), time.perf_counter() - t1))
    out.append(("total", sum(c for n, c, _ in out if n != "index"),
                time.perf_counter() - t0))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.sdlint",
        description="spacedrive_tpu concurrency & invariant analyzer")
    ap.add_argument("--root", default=repo_root(),
                    help="repo root (default: auto)")
    ap.add_argument("--passes", nargs="?", const="?list", default="",
                    help="comma-separated subset of passes; with no "
                         "value, list the registered passes and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=DEFAULT_PATH,
                    help="baseline file path")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="prune stale baseline entries + lower budget "
                         "(never adds)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="bootstrap: write ALL current findings as the "
                         "baseline (policy: one-time, review-visible)")
    ap.add_argument("--flag-table", action="store_true",
                    help="print the generated README flag table and exit")
    ap.add_argument("--timeout-table", action="store_true",
                    help="print the generated README timeout table "
                         "and exit")
    ap.add_argument("--backoff-table", action="store_true",
                    help="print the generated README backoff-policy "
                         "table and exit")
    ap.add_argument("--chan-table", action="store_true",
                    help="print the generated README channel table "
                         "and exit")
    ap.add_argument("--owner-table", action="store_true",
                    help="print the generated thread-ownership "
                         "contract table and exit")
    ap.add_argument("--sql-table", action="store_true",
                    help="print the generated SQL statement-contract "
                         "table (the store's read/write seam) and exit")
    ap.add_argument("--artifact-table", action="store_true",
                    help="print the generated durable-artifact "
                         "registry table (the persist seam) and exit")
    ap.add_argument("--wire-table", action="store_true",
                    help="print the generated wire message-contract "
                         "table (the p2p frame seam) and exit")
    ap.add_argument("--write-wire-baseline", action="store_true",
                    help="regenerate tools/sdlint/wire_baseline.json "
                         "from the registry (the diff IS the compat "
                         "review; pair schema changes with a "
                         "PROTO_VERSIONS bump)")
    ap.add_argument("--stats", action="store_true",
                    help="per-pass finding counts and wall-time "
                         "(informational; exit 0)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="incremental pre-commit mode: lint only files "
                         "touched vs REF (default HEAD; worktree + "
                         "index + untracked) plus their reverse "
                         "call-graph closure")
    args = ap.parse_args(argv)

    if args.no_baseline and (args.update_baseline or args.write_baseline):
        ap.error("--no-baseline cannot be combined with "
                 "--update-baseline/--write-baseline (it would rewrite "
                 "the baseline from an empty view)")
    if args.changed is not None and (args.update_baseline
                                     or args.write_baseline):
        ap.error("--changed cannot be combined with "
                 "--update-baseline/--write-baseline (a partial view "
                 "must never rewrite the whole-tree baseline)")

    if args.flag_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import flags
        print(flags.flag_table_markdown())
        return 0

    if args.timeout_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import timeouts
        print(timeouts.timeout_table_markdown())
        return 0

    if args.backoff_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import timeouts
        print(timeouts.backoff_table_markdown())
        return 0

    if args.chan_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import channels
        print(channels.chan_table_markdown())
        return 0

    if args.owner_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import threadctx
        print(threadctx.owner_table_markdown())
        return 0

    if args.sql_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu.store import statements
        print(statements.sql_table_markdown())
        return 0

    if args.artifact_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu import persist
        print(persist.artifact_table_markdown())
        return 0

    if args.wire_table:
        sys.path.insert(0, args.root)
        from spacedrive_tpu.p2p import wire
        print(wire.wire_table_markdown())
        return 0

    if args.write_wire_baseline:
        sys.path.insert(0, args.root)
        from spacedrive_tpu.p2p import wire
        from .passes import _wire
        path = os.path.join(args.root, _wire.BASELINE_PATH)
        doc = {
            "_comment": "Wire-contract snapshot (proto-compat pass). "
                        "Regenerate with --write-wire-baseline; a "
                        "schema change must land WITH a "
                        "PROTO_VERSIONS bump or the pass flags "
                        "schema-no-bump.",
            "messages": wire.baseline_snapshot(),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wire baseline written: {len(doc['messages'])} "
              f"message(s) -> {_wire.BASELINE_PATH}")
        return 0

    if args.stats:
        for name, count, secs in stats(args.root):
            print(f"{name:22s} {count:4d} finding(s) {secs:7.2f}s")
        return 0

    if args.passes == "?list":
        from .passes import PASSES
        for name in PASSES:
            print(name)
        return 0

    pass_names = [p.strip() for p in args.passes.split(",") if p.strip()]
    passes = get_passes(pass_names or None)
    project = load_project(args.root)
    scope_paths = None
    if args.changed is not None:
        try:
            touched = git_changed_paths(args.root, args.changed)
        except RuntimeError as e:
            print(f"sdlint: --changed: {e}", file=sys.stderr)
            return 2
        known = {f.relpath for f in project.files}
        # "Deleted" = in a lint scope, absent from the index, and NOT
        # merely excluded from linting (tools/sdlint/* edits its own
        # analyzer — those are never in `known` yet clearly exist).
        deleted = [p for p in touched
                   if p.endswith(".py") and p not in known
                   and p.startswith(tuple(s + "/" for s in
                                          DEFAULT_SCOPES))
                   and not p.startswith(EXCLUDE_PREFIXES)
                   and "__pycache__" not in p
                   and not os.path.exists(os.path.join(args.root, p))]
        if deleted:
            # A deleted/renamed module's CALLERS are exactly what the
            # change can break, but the file is gone from the current
            # index so the closure cannot be seeded from it — fall
            # back to the whole tree rather than silently skipping.
            print(f"sdlint: --changed: {len(deleted)} in-scope "
                  f"file(s) deleted/renamed vs {args.changed} "
                  f"({deleted[0]}…) — falling back to a full-tree "
                  "run", file=sys.stderr)
        else:
            scope_paths = reverse_closure_files(project, touched)
            if not scope_paths:
                print(f"sdlint: no lintable files changed vs "
                      f"{args.changed}")
                return 0
            # Re-index over the scoped subset: passes run on (and pay
            # for) only the changed files plus their reverse callers.
            # Whole-tree invariants (lock graph, registry drift) are
            # judged on the subset view — the full gate stays
            # tier-1's job.
            project = Project(args.root,
                              [f for f in project.files
                               if f.relpath in scope_paths],
                              project.problems)
            print(f"sdlint: --changed {args.changed}: {len(touched)} "
                  f"touched file(s) -> {len(scope_paths)} in "
                  f"reverse-closure scope", file=sys.stderr)
    findings = run_passes(project, passes)
    # A subset run must not judge (or prune!) other passes' baseline
    # entries: out-of-scope keys are carved out and merged back on save.
    out_of_scope = {}

    if args.write_baseline:
        bl = Baseline({f.key(): f.message for f in findings},
                      budget=len({f.key() for f in findings}))
        bl.save(args.baseline)
        print(f"baseline written: {len(bl.entries)} entr(y/ies), "
              f"budget {bl.budget}")
        return 0

    bl = Baseline({}, 0) if args.no_baseline else Baseline.load(args.baseline)
    if pass_names:
        ran = set(pass_names) | {"core"}
        out_of_scope = {k: v for k, v in bl.entries.items()
                        if k.split("::", 1)[0] not in ran}
        bl.entries = {k: v for k, v in bl.entries.items()
                      if k not in out_of_scope}
    if scope_paths is not None:
        # Same carve by PATH for incremental runs: baseline entries for
        # files outside the closure are neither judged nor stale (key
        # layout: pass::code::path::qual::ident).
        def _in_scope(key: str) -> bool:
            parts = key.split("::")
            return len(parts) > 2 and parts[2] in scope_paths
        out_of_path = {k: v for k, v in bl.entries.items()
                       if not _in_scope(k)}
        bl.entries = {k: v for k, v in bl.entries.items()
                      if k not in out_of_path}
        out_of_scope.update(out_of_path)
    new, baselined, stale = bl.split(findings)
    if scope_paths is not None:
        # Subset views lose interprocedural findings whose chains
        # leave the closure — "stale" there is an artifact, not a
        # fixed finding. Suppress it in BOTH output modes so a
        # --changed --json consumer can never prune live entries.
        stale = []

    if args.update_baseline:
        dropped = bl.prune(findings)
        bl.entries.update(out_of_scope)
        bl.budget += len(out_of_scope)
        bl.save(args.baseline)
        print(f"baseline: dropped {len(dropped)} stale entr(y/ies), "
              f"{len(bl.entries)} remain, budget {bl.budget}")

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_json() for f in new],
            "baselined": [f.as_json() for f in baselined],
            "stale_baseline_keys": stale,
            "budget": bl.budget,
        }, indent=2))
    else:
        for f in new:
            print(f.text())
        if stale and not args.update_baseline and scope_paths is None:
            # Incremental runs skip the nudge: a subset view loses
            # interprocedural findings whose chains leave the closure,
            # so "stale" there is an artifact, not a fixed finding.
            print(f"note: {len(stale)} stale baseline entr(y/ies) — run "
                  f"--update-baseline to shrink the file",
                  file=sys.stderr)
        print(f"sdlint: {len(new)} new finding(s), "
              f"{len(baselined)} baselined, {len(stale)} stale")
    if bl.over_budget():
        print("sdlint: baseline exceeds its budget — entries were added "
              "by hand without raising the budget (see baseline.py "
              "policy)", file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
