"""sdlint pass registry."""

from __future__ import annotations

from typing import List, Optional

from .blocking_async import BlockingAsyncPass
from .lock_discipline import LockDisciplinePass
from .crdt_parity import CrdtParityPass
from .flag_registry import FlagRegistryPass
from .telemetry import TelemetryPass
from .jit_stability import JitStabilityPass
from .dtype_discipline import DtypeDisciplinePass
from .host_transfer import HostTransferPass
from .task_lifecycle import TaskLifecyclePass
from .cancellation_safety import CancellationSafetyPass
from .timeout_discipline import TimeoutDisciplinePass
from .queue_discipline import QueueDisciplinePass
from .backpressure import BackpressurePass
from .unbounded_growth import UnboundedGrowthPass
from .shared_mutation import SharedMutationPass
from .thread_boundary import ThreadBoundaryPass
from .guard_consistency import GuardConsistencyPass
from .sql_discipline import SqlDisciplinePass
from .tx_shape import TxShapePass
from .schema_parity import SchemaParityPass
from .io_durability import IoDurabilityPass
from .crash_atomicity import CrashAtomicityPass
from .tmp_hygiene import TmpHygienePass
from .wire_discipline import WireDisciplinePass
from .schema_drift import SchemaDriftPass
from .proto_compat import ProtoCompatPass

PASSES = {
    p.name: p for p in (
        BlockingAsyncPass(), LockDisciplinePass(), CrdtParityPass(),
        FlagRegistryPass(), TelemetryPass(), JitStabilityPass(),
        DtypeDisciplinePass(), HostTransferPass(),
        TaskLifecyclePass(), CancellationSafetyPass(),
        TimeoutDisciplinePass(),
        QueueDisciplinePass(), BackpressurePass(),
        UnboundedGrowthPass(),
        SharedMutationPass(), ThreadBoundaryPass(),
        GuardConsistencyPass(),
        SqlDisciplinePass(), TxShapePass(), SchemaParityPass(),
        IoDurabilityPass(), CrashAtomicityPass(), TmpHygienePass(),
        WireDisciplinePass(), SchemaDriftPass(), ProtoCompatPass(),
    )
}


def all_passes() -> List:
    return list(PASSES.values())


def get_passes(names: Optional[List[str]]) -> List:
    if not names:
        return all_passes()
    out = []
    for n in names:
        if n not in PASSES:
            raise KeyError(
                f"unknown pass {n!r} (have: {', '.join(sorted(PASSES))})")
        out.append(PASSES[n])
    return out
