"""Pass: tx-shape — write transactions have the right granularity.

The single-writer store lives or dies by transaction shape: a tx per
item serializes the whole job on COMMIT latency (the PR 1 identifier
fix), a blocking call inside a tx holds the write lock for its
duration, and a nested tx is a guaranteed runtime error. Codes:

- `tx-in-loop`        — a transaction opened PER ITERATION of a
  For/While loop: a lexical `with ...tx()/write_ops()`, a `run_tx`,
  a Database helper without `conn=`, or a call to a resolvable
  function whose own body opens one. Batch under ONE tx (the
  commit-per-item shape; sd_sql_tx_statements shows it at runtime as
  a spike at 1-2 statements/tx).
- `blocking-in-tx`    — a blocking call (file IO, sleep, subprocess,
  parameterless .result()/.join(), network sends) lexically inside a
  tx body: the write lock is held the whole time. Hashing/stat work
  belongs BEFORE the tx.
- `await-in-tx`       — an `await` inside a sync-with tx body (the
  coroutine suspends holding the write lock; lock-discipline's
  await-under-lock sibling, keyed to tx() specifically).
- `nested-tx-chain`   — a call INSIDE a tx body (no conn= passed) to
  a function that transitively opens its own tx. lock-discipline
  catches the direct `db.helper()`/`.tx()` forms; this code follows
  resolvable project-function chains.
- `executemany-candidate` — the same single-row write statement
  (`run(<write>)` / INSERT/UPDATE literal) executed per loop
  iteration where a batched form (`run_many` / `insert_many`) would
  collapse the Python/sqlite statement loop. Advisory: sites with a
  real per-row dependency waive inline with the reason.
- `actor-bypass`       — product code (spacedrive_tpu/ outside
  store/) opening a raw `db.tx()` or calling `run_tx()` directly.
  The raw transaction primitive bypasses the write actor: no group
  commit, no sd_store_group_* attribution, no store.group_commit
  chaos coverage, and it contends with the actor for the write lock.
  Product writers go through `write_tx()` / `submit_write()`;
  engine-room, bootstrap and migration sites waive inline with the
  reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk
from . import _sql

PASS = "tx-shape"

_TX_LASTS = {"tx", "write_tx", "write_ops"}
# Receivers that make a bare `.tx` attribute a Database transaction
# (dotted part right before the method) — keeps actor-bypass from
# firing on unrelated attrs that happen to be named `tx`.
_DB_RECEIVERS = {"db", "_db", "database"}
_DB_HELPERS = {"insert", "insert_many", "update", "upsert", "delete"}

_BLOCKING_LASTS = {
    "sleep", "open", "system", "run", "check_output", "check_call",
    "copyfile", "copytree", "rmtree", "urlopen", "sendall", "recv",
}
_BLOCKING_PREFIXES = ("subprocess", "shutil", "requests", "urllib")


def _opens_own_tx(fn: FuncInfo) -> bool:
    """Does this function's own body open a write transaction?"""
    for node in own_body_walk(fn.node):
        if isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    d = dotted(item.context_expr.func)
                    if d is not None and \
                            d.split(".")[-1] in _TX_LASTS:
                        return True
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None:
                continue
            last = d.split(".")[-1]
            if last == "run_tx":
                return True
            if last in _DB_HELPERS and d.split(".")[-2:-1] == ["db"] \
                    and not any(kw.arg == "conn"
                                for kw in node.keywords):
                return True
    return False


def _tx_opening_closure(project: Project) -> Set[str]:
    """Quals of functions that open a tx directly or via resolvable
    calls (fixed point over the call graph)."""
    direct = {fn.qual for fn in project.index.funcs
              if _opens_own_tx(fn)}
    opening = set(direct)
    changed = True
    while changed:
        changed = False
        for fn in project.index.funcs:
            if fn.qual in opening:
                continue
            for site in fn.calls:
                if any(kw.arg == "conn" for kw in site.node.keywords):
                    continue  # rides the caller's tx — not an opener
                callee = project.index.resolve(fn, site.name)
                if callee is not None and callee.qual in opening:
                    opening.add(fn.qual)
                    changed = True
                    break
    return opening


def _is_blocking(call: ast.Call) -> Optional[str]:
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    if d == "time.sleep" or (last == "sleep" and parts[0] == "time"):
        return d
    if last == "open" and len(parts) == 1:
        return d
    if parts[0] in _BLOCKING_PREFIXES:
        return d
    if last in ("result", "join") and not call.args \
            and not call.keywords and not any(
                "task" in p for p in parts[:-1]):
        return d
    return None


class _TxWalker:
    """Track tx nesting through one function's own statements."""

    def __init__(self, fn: FuncInfo, project: Project,
                 openers: Set[str], decls, findings: List[Finding]):
        self.fn = fn
        self.project = project
        self.openers = openers
        self.decls = decls
        self.findings = findings

    def _emit(self, code, ident, msg, lineno):
        self.findings.append(Finding(
            PASS, code, self.fn.src.relpath, self.fn.qual, ident,
            msg, lineno))

    def scan(self):
        self._block(self.fn.node.body, in_tx=False, in_loop=False)

    def _block(self, stmts, in_tx: bool, in_loop: bool):
        for stmt in stmts:
            self._stmt(stmt, in_tx, in_loop)

    def _stmt(self, node, in_tx: bool, in_loop: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.With):
            opens = False
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    d = dotted(item.context_expr.func)
                    if d is not None and \
                            d.split(".")[-1] in _TX_LASTS:
                        opens = True
                        if in_loop:
                            self._emit(
                                "tx-in-loop", d,
                                f"`with {d}()` per loop iteration — "
                                "the commit-per-item shape; batch "
                                "the loop under ONE transaction",
                                node.lineno)
            self._block(node.body, in_tx or opens, in_loop)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            self._block(node.body, in_tx, in_loop=True)
            self._block(node.orelse, in_tx, in_loop=True)
            return
        if isinstance(node, ast.Await):
            if in_tx:
                self._emit(
                    "await-in-tx", "await",
                    "`await` inside an open tx() — the coroutine "
                    "suspends holding the write lock", node.lineno)
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    self._call(sub, in_tx, in_loop, awaited=True)
            return
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.Call):
                self._call(sub, in_tx, in_loop)
                for inner in ast.iter_child_nodes(sub):
                    self._stmt(inner, in_tx, in_loop)
            else:
                self._stmt(sub, in_tx, in_loop)

    def _call(self, call: ast.Call, in_tx: bool, in_loop: bool,
              awaited: bool = False):
        d = dotted(call.func)
        if d is None:
            return
        last = d.split(".")[-1]
        has_conn = any(kw.arg == "conn" for kw in call.keywords)
        # per-iteration tx openers
        if in_loop and not has_conn:
            if last == "run_tx":
                self._emit(
                    "tx-in-loop", d,
                    "run_tx() per loop iteration — batch under ONE "
                    "tx() with run(conn=)", call.lineno)
            elif last in _DB_HELPERS and "db" in d.split(".")[:-1]:
                self._emit(
                    "tx-in-loop", d,
                    f"db.{last}() without conn= per loop iteration "
                    "opens a tx each time — batch under ONE tx()",
                    call.lineno)
            else:
                callee = self.project.index.resolve(self.fn, d)
                if callee is not None and callee.qual in self.openers \
                        and last not in _TX_LASTS:
                    self._emit(
                        "tx-in-loop", d,
                        f"{d}() opens its own transaction and is "
                        "called per loop iteration", call.lineno)
        if in_tx:
            blocking = _is_blocking(call)
            if blocking is not None and not awaited:
                self._emit(
                    "blocking-in-tx", blocking,
                    f"blocking call `{blocking}` inside an open tx() "
                    "holds the write lock for its duration",
                    call.lineno)
            if not has_conn and last not in _TX_LASTS:
                callee = self.project.index.resolve(self.fn, d)
                if callee is not None and callee.qual in self.openers:
                    self._emit(
                        "nested-tx-chain", d,
                        f"{d}() (transitively) opens its own tx "
                        "inside this open tx() — pass conn= through",
                        call.lineno)
        # executemany candidate: single-row declared write per loop
        if in_loop and last == "run" and call.args and has_conn:
            name_node = call.args[0]
            if isinstance(name_node, ast.Constant) and isinstance(
                    name_node.value, str):
                decl = self.decls.get(name_node.value)
                if decl is not None and decl.verb == "write" and \
                        _sql.sql_head(decl.sql) in ("INSERT", "UPDATE"):
                    self._emit(
                        "executemany-candidate", name_node.value,
                        f"write statement {name_node.value!r} "
                        "executed per loop iteration — run_many() "
                        "collapses the statement loop", call.lineno)


def _actor_bypass(fn: FuncInfo, findings: List[Finding]) -> None:
    """Flag raw Database.tx()/run_tx() from product code: every
    product writer must ride the group-commit actor (write_tx /
    submit_write). The store package itself is the engine room — the
    actor brackets its groups with the raw tx() — and tests/tools sit
    outside the product write path."""
    rel = fn.src.relpath
    if not rel.startswith("spacedrive_tpu/") or \
            rel.startswith("spacedrive_tpu/store/"):
        return
    for node in own_body_walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        parts = d.split(".")
        raw_tx = parts[-1] == "tx" and len(parts) >= 2 \
            and parts[-2] in _DB_RECEIVERS
        if raw_tx or parts[-1] == "run_tx":
            findings.append(Finding(
                PASS, "actor-bypass", rel, fn.qual, d,
                f"`{d}()` opens a raw transaction around the write "
                "actor — no group commit, no sd_store_group_* "
                "attribution, no store.group_commit chaos coverage. "
                "Use write_tx()/submit_write(); bootstrap/migration "
                "sites waive inline with the reason", node.lineno))


class TxShapePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        decls = _sql.project_decls(project)
        openers = _tx_opening_closure(project)
        findings: List[Finding] = []
        for fn in project.index.funcs:
            _TxWalker(fn, project, openers, decls, findings).scan()
            _actor_bypass(fn, findings)
        return findings
