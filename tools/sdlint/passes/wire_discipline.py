"""Pass: wire-discipline — every cross-node frame speaks by declared name.

PR 12 proved the registry + static-pass + runtime-twin shape on SQL,
round 19 on durable writes; this round applies it to the p2p wire
surface. Every message kind a tunnel carries is DECLARED in
`spacedrive_tpu/p2p/wire.py` (`declare_message`: schema tokens,
direction, size cap, timeout budget) and built/validated by name
through `wire.pack` / `wire.unpack` — so a payload cannot drift from
its declaration, and the frame auditor armed by sanitize.install()
holds live traffic to the same contracts.

Scope: the wire-plane product modules (`spacedrive_tpu/p2p/`,
`spacedrive_tpu/sync/`) plus files opting in with a
`# sdlint-scope: wire` marker in their first five lines (fixtures).
wire.py itself is exempt — it IS the registry.

Codes:

- ``undeclared-kind``: `wire.pack`/`wire.unpack` (or a registry read
  like `wire.proto`/`wire.slice_cap`) naming a message absent from
  the declarations — the call raises WireError at runtime; declare
  the contract first.
- ``dynamic-kind``: pack/unpack with a non-literal name — the static
  passes, the README inventory, and the malformed-frame grid must
  see every kind; a data-driven kind waives with the reason (the obs
  client's four-contract fetch is the sanctioned case).
- ``raw-kind-literal``: a hand-built dict literal carrying a declared
  t/kind discriminator value outside wire.py — pack() fills
  discriminators itself, so legit code never writes one; a literal
  frame bypasses schema/const/size validation entirely.
- ``raw-value-literal``: a declared bare-string verdict ('ok',
  'accept', ...) passed literally to a send — the values contract
  (`wire.pack(name, value=...)`) is how the verdict stays in its
  declared set.
- ``computed-declaration``: a `declare_message` call whose
  name/schema is not literal — invisible to every static consumer
  (this pass, the snapshot diff, the grid).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Project
from . import _wire

PASS = "wire-discipline"


class WireDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        decls = _wire.project_decls(project)
        consts = _wire.const_index(decls)
        values = _wire.value_index(decls)
        findings: List[Finding] = []

        # computed-declaration applies everywhere a declaration is
        # attempted, scope or not — the registry must stay literal.
        for src in project.files:
            if src.relpath == _wire.WIRE_PATH:
                continue
            in_scope = _wire.in_scope(src)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call):
                    d = node.func
                    name = getattr(d, "attr", None) or \
                        getattr(d, "id", None)
                    if name == "declare_message":
                        first = node.args[0] if node.args else None
                        if not (isinstance(first, ast.Constant)
                                and isinstance(first.value, str)):
                            findings.append(Finding(
                                PASS, "computed-declaration",
                                src.relpath, "", "non-literal",
                                "declare_message with a non-literal "
                                "name: invisible to the static "
                                "passes, the snapshot diff, and the "
                                "malformed-frame grid",
                                node.lineno))
                if not in_scope:
                    continue
                if isinstance(node, ast.Dict):
                    self._check_dict_literal(
                        src, node, consts, findings)

        for fn in project.index.funcs:
            src = fn.src
            if not _wire.in_scope(src):
                continue
            bound = _wire.imports_wire(src.tree)
            for site in fn.calls:
                api = _wire.wire_call(site.name, bound)
                call = site.node
                if api in _wire.PACK_APIS or api in ("slice_cap",
                                                     "message"):
                    first = call.args[0] if call.args else None
                    if not (isinstance(first, ast.Constant)
                            and isinstance(first.value, str)):
                        findings.append(Finding(
                            PASS, "dynamic-kind", src.relpath,
                            fn.qual, f"wire.{api}",
                            f"wire.{api} with a non-literal message "
                            "name: the inventory, the grid, and the "
                            "drift checks must see every kind — "
                            "waive with the reason if the kind is "
                            "genuinely data",
                            call.lineno))
                    elif first.value not in decls:
                        findings.append(Finding(
                            PASS, "undeclared-kind", src.relpath,
                            fn.qual, first.value,
                            f"wire message {first.value!r} is not "
                            "declared in spacedrive_tpu/p2p/wire.py "
                            "(declare_message)",
                            call.lineno))
                elif api == "proto":
                    first = call.args[0] if call.args else None
                    if isinstance(first, ast.Constant) and \
                            isinstance(first.value, str) and \
                            first.value not in _wire.proto_versions(
                                project.root):
                        findings.append(Finding(
                            PASS, "undeclared-kind", src.relpath,
                            fn.qual, first.value,
                            f"proto group {first.value!r} is not in "
                            "wire.PROTO_VERSIONS",
                            call.lineno))
                # a declared verdict string sent literally bypasses
                # the values contract
                last = site.name.rsplit(".", 1)[-1]
                if last in ("send", "send_nowait") and call.args:
                    arg = call.args[0]
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str) and \
                            arg.value in values:
                        findings.append(Finding(
                            PASS, "raw-value-literal", src.relpath,
                            fn.qual, arg.value,
                            f"literal verdict {arg.value!r} sent "
                            "raw: route it through wire.pack("
                            f"{values[arg.value]!r}, value=...) so "
                            "the declared value set is enforced",
                            call.lineno))
        return findings

    def _check_dict_literal(self, src, node: ast.Dict, consts,
                            findings: List[Finding]) -> None:
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and k.value in ("t", "kind")):
                continue
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                continue
            key = f"{k.value}={v.value}"
            name = consts.get(key)
            if name is not None:
                findings.append(Finding(
                    PASS, "raw-kind-literal", src.relpath, "", key,
                    f"hand-built frame dict with discriminator "
                    f"{key} (declared message {name!r}): pack() "
                    "fills discriminators itself — a literal frame "
                    "bypasses schema/const/size validation",
                    node.lineno))
