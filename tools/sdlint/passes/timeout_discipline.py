"""Pass: timeout-discipline — no unbounded network awaits.

A peer that stops talking must cost a BUDGET, not a hung coroutine:
before this pass the spacedrop verdict wait was the only network await
in the tree with any timeout — a stalled clone ack, a silent dialer,
or a dead websocket subscriber parked its coroutine forever (and, at
shutdown, became a supervisor orphan). The discipline mirrors the
PR 5 jit-contract registry: every timeout is DECLARED by name in
`spacedrive_tpu/timeouts.py` (defaults scaled by SDTPU_TIMEOUT_SCALE,
README table generated from the registry) and applied with
`await with_timeout("name", <net await>)` or a block-scoped
``async with deadline("name"):``.

Scope: modules under `spacedrive_tpu/{p2p,api,sync}/` — the layers
that talk to sockets/tunnels/websockets — plus any file carrying an
``# sdlint-scope: net`` marker in its head (how fixtures opt in).

Network roots (the awaits that must be budgeted):

- frame/stream primitives by name: `readexactly`, `readuntil`,
  `read_frame`, `read_msg`, `open_connection` (`tunnel_handshake`
  budgets itself — see proto.py);
- `recv`/`recv_raw`/`send`/`send_raw`/`drain` — bare, or on a
  receiver that names the wire (`tunnel`, `ws`, `reader`, `writer`,
  `resp`, `sock`, `stream`);
- websocket/HTTP streaming methods on `ws`/`resp`/`request`
  receivers: `send_json`, `send_str`, `prepare`, `receive`, `write`,
  `write_eof`, `json`, `text`.

`async for` over a websocket is NOT a root by design: a server's
client-read loop is legitimately idle-forever (the client owns that
cadence; slow-request bounds live in api.http.read/write). Transport
primitives (`proto.py` internals) carry explicit suppression markers:
their budget lives at the call site, which this pass enforces.

Codes: ``no-timeout`` (root await with no budget), ``unnamed-timeout``
(raw `asyncio.wait_for` around a root — literals drifted once
already; use the registry), ``undeclared-timeout`` (a `with_timeout`/
`deadline` name missing from the registry), ``dynamic-timeout-name``
(non-literal name: the table must be static).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "timeout-discipline"

SCOPE_PREFIXES = ("spacedrive_tpu/p2p/", "spacedrive_tpu/api/",
                  "spacedrive_tpu/sync/")
SCOPE_MARKER = "# sdlint-scope: net"
CENTRAL = "spacedrive_tpu/timeouts.py"

# `tunnel_handshake` is NOT a root: it owns its own `p2p.handshake`
# deadline internally (proto.py), so callers need no second budget.
_NAMED_ROOTS = {"readexactly", "readuntil", "read_frame", "read_msg",
                "open_connection"}
_WIRE_METHODS = {"recv", "recv_raw", "send", "send_raw", "drain"}
_WIRE_RECEIVERS = {"tunnel", "ws", "reader", "writer", "resp", "sock",
                   "stream"}
_HTTP_METHODS = {"send_json", "send_str", "prepare", "receive",
                 "write", "write_eof", "json", "text"}
_HTTP_RECEIVERS = {"ws", "resp", "request"}


def declared_timeouts(root: str) -> Dict[str, float]:
    """Budgets from `declare_timeout(...)` calls in the central
    registry (AST — the linted tree is never imported)."""
    out: Dict[str, float] = {}
    path = os.path.join(root, CENTRAL)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "declare_timeout" and node.args):
            continue
        name = node.args[0]
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            default = 0.0
            if len(node.args) > 1 and \
                    isinstance(node.args[1], ast.Constant):
                default = float(node.args[1].value)
            out[name.value] = default
    return out


def classify_root(call: ast.Call) -> str:
    """Stable ident of the network root this call is, else ''."""
    d = dotted(call.func)
    if d is None:
        return ""
    parts = d.split(".")
    last = parts[-1]
    recv = [p.lower() for p in parts[:-1] if p not in ("self", "cls")]
    if last in _NAMED_ROOTS:
        return d
    if last in _WIRE_METHODS and (
            not recv or any(r in _WIRE_RECEIVERS for r in recv)):
        return d
    if last in _HTTP_METHODS and any(
            r in _HTTP_RECEIVERS for r in recv):
        return d
    return ""


def _last(call_or_name) -> str:
    d = dotted(call_or_name.func) if isinstance(call_or_name, ast.Call) \
        else dotted(call_or_name)
    return d.rsplit(".", 1)[-1] if d else ""


class TimeoutDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_timeouts(project.root)
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            rel = fn.src.relpath
            if rel == CENTRAL:
                continue  # the registry's own wait_for IS the wrapper
            head = "\n".join(fn.src.lines[:5])
            if not (rel.startswith(SCOPE_PREFIXES)
                    or SCOPE_MARKER in head):
                continue
            self._check_fn(fn, rel, declared, emit)
        return findings

    def _check_fn(self, fn, rel: str, declared: Dict[str, float],
                  emit) -> None:
        # Node ids covered by an `async with deadline("name"):` block.
        covered: Set[int] = set()
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.AsyncWith):
                continue
            for item in node.items:
                cm = item.context_expr
                if not (isinstance(cm, ast.Call)
                        and _last(cm) == "deadline"):
                    continue
                self._check_name(cm, rel, fn.qual, declared, emit)
                for stmt in node.body:
                    covered.add(id(stmt))
                    for sub in ast.walk(stmt):
                        covered.add(id(sub))
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Await):
                continue
            v = node.value
            if not isinstance(v, ast.Call):
                continue
            last = _last(v)
            if last == "with_timeout":
                self._check_name(v, rel, fn.qual, declared, emit)
                continue
            if last == "wait_for":
                inner = v.args[0] if v.args else None
                if isinstance(inner, ast.Call) and classify_root(inner):
                    emit(Finding(
                        PASS, "unnamed-timeout", rel, fn.qual,
                        f"wait_for:{classify_root(inner)}",
                        f"raw asyncio.wait_for around "
                        f"`{classify_root(inner)}`: budgets live in "
                        "the timeouts.py registry — use "
                        "with_timeout(\"<name>\", ...)",
                        node.lineno))
                continue
            root = classify_root(v)
            if root and id(node) not in covered:
                emit(Finding(
                    PASS, "no-timeout", rel, fn.qual, root,
                    f"unbounded network await `{root}`: wrap in "
                    "with_timeout(\"<name>\", ...) or a "
                    "deadline(\"<name>\") block (timeouts.py)",
                    node.lineno))

    def _check_name(self, call: ast.Call, rel: str, qual: str,
                    declared: Dict[str, float], emit) -> None:
        arg = call.args[0] if call.args else None
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            emit(Finding(
                PASS, "dynamic-timeout-name", rel, qual,
                "non-literal",
                "timeout name must be a string literal so the budget "
                "table stays static",
                call.lineno))
            return
        if arg.value not in declared:
            emit(Finding(
                PASS, "undeclared-timeout", rel, qual, arg.value,
                f"timeout {arg.value!r} is not declared in "
                "spacedrive_tpu/timeouts.py",
                call.lineno))
