"""Pass: lock-discipline — what may happen while a threading lock is held.

The store's single-writer discipline (store/db.py) rests on rules the
type system cannot see:

- `await-under-lock`   — an `await` lexically inside a sync
  `with <lock>:` block suspends the coroutine with the lock held;
  every other task needing it deadlocks behind a owner that only
  resumes via the same loop. (`async with` asyncio locks are exempt —
  they are designed to be held across awaits.)
- `wait-under-lock`    — a cross-thread wait (`future.result()`,
  `thread.join()`, `queue.join()`, `time.sleep`) while holding a lock:
  if the thread being waited on needs that same lock, the process
  hangs. This is the PR 1 `store/db.py` deadlock shape: connection
  registration serialized on the WRITE lock while the writer held it
  waiting on reader-thread prefetch results. The fix moved
  registration to its own leaf lock; the fixture
  (tests/fixtures/sdlint/locks_bad.py) preserves the bad shape and
  this pass must keep catching it.
- `nested-write-tx`    — entering a write transaction (`db.tx()`,
  `sync.write_ops()`, or a Database helper without `conn=`) inside an
  open `with tx()/write_ops()` block: SQLite raises "cannot start a
  transaction within a transaction" at runtime; statically it is
  always a bug.
- `lock-order-cycle`   — a project-wide lock graph built from nested
  `with <lock>` statements (plus one interprocedural hop: calls inside
  a lock body to resolvable functions that acquire locks); a cycle in
  the graph is a potential AB/BA deadlock. Lock identity is the
  terminal attribute name (`self._write_lock` and `db._write_lock`
  are the same lock family by this codebase's naming discipline).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk

PASS = "lock-discipline"

_WAIT_LASTS = {"result", "join"}   # parameterless → cross-thread wait
_TX_LASTS = {"tx", "write_ops"}
# Database entry points that open their OWN tx unless handed conn=
# (run_tx always does — it is the single-statement-tx sugar).
_DB_HELPERS = {"insert", "insert_many", "update", "upsert", "delete",
               "execute", "run_tx"}


def lock_name(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity for `with X:` — the terminal name when
    it smells like a threading lock (`*_lock` / `*_mutex` / `lock`)."""
    d = dotted(expr)
    if d is None:
        return None
    last = d.split(".")[-1]
    if last.endswith(("_lock", "_mutex")) or last in ("lock", "mutex"):
        return last
    return None


def _tx_ctx(expr: ast.AST) -> Optional[str]:
    """'tx' / 'write_ops' when `with X` opens a write transaction."""
    if isinstance(expr, ast.Call):
        d = dotted(expr.func)
        if d is not None and d.split(".")[-1] in _TX_LASTS:
            return d.split(".")[-1]
    return None


def _is_wait(call: ast.Call) -> Optional[str]:
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    if last in _WAIT_LASTS and not call.args and not call.keywords \
            and not any("task" in p for p in parts[:-1]):
        return d
    if d == "time.sleep":
        return d
    return None


def _opens_nested_tx(call: ast.Call) -> Optional[str]:
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    recv = parts[:-1]
    if last in _TX_LASTS and recv and recv[-1] in ("db", "sync"):
        return d
    if last in _DB_HELPERS and recv and recv[-1] == "db":
        # Database helpers open their own tx UNLESS handed the open
        # connection via conn=...
        if not any(kw.arg == "conn" for kw in call.keywords):
            return d
    return None


class _FnScanner:
    """Walk one function, tracking the stack of held with-contexts."""

    def __init__(self, fn: FuncInfo, project: Project,
                 edges: Dict[str, Set[str]],
                 edge_sites: Dict[Tuple[str, str], Tuple[str, int]],
                 findings: List[Finding]):
        self.fn = fn
        self.project = project
        self.edges = edges
        self.edge_sites = edge_sites
        self.findings = findings

    def scan(self) -> None:
        self._visit_block(self.fn.node.body, locks=[], txs=[])

    # -- helpers -----------------------------------------------------------

    def _note_edge(self, outer: str, inner: str, lineno: int) -> None:
        if outer == inner:
            return
        self.edges.setdefault(outer, set()).add(inner)
        self.edge_sites.setdefault(
            (outer, inner), (self.fn.src.relpath, lineno))

    def _emit(self, code: str, ident: str, msg: str, lineno: int) -> None:
        self.findings.append(Finding(
            PASS, code, self.fn.src.relpath, self.fn.qual, ident,
            msg, lineno))

    # -- walk --------------------------------------------------------------

    def _visit_block(self, stmts, locks: List[str], txs: List[str]) -> None:
        for stmt in stmts:
            self._visit_stmt(stmt, locks, txs)

    def _visit_stmt(self, node: ast.AST, locks: List[str],
                    txs: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested bodies run later, not under these locks
        if isinstance(node, ast.With):
            new_locks, new_txs = list(locks), list(txs)
            for item in node.items:
                ln = lock_name(item.context_expr)
                if ln is not None:
                    for held in new_locks:
                        self._note_edge(held, ln, node.lineno)
                    new_locks.append(ln)
                    continue
                tx = _tx_ctx(item.context_expr)
                if tx is not None:
                    if new_txs:
                        self._emit(
                            "nested-write-tx", f"{new_txs[-1]}>{tx}",
                            f"`with ...{tx}()` entered inside an open "
                            f"`{new_txs[-1]}()` transaction (SQLite "
                            f"cannot nest write transactions)",
                            node.lineno)
                    new_txs.append(tx)
                # with-expressions are also expressions: scan them
                self._visit_expr_tree(item.context_expr, locks, txs)
            self._visit_block(node.body, new_locks, new_txs)
            return
        if isinstance(node, ast.Await):
            if locks:
                self._emit(
                    "await-under-lock", f"await@{locks[-1]}",
                    f"`await` while holding lock {locks[-1]!r} — the "
                    f"coroutine suspends mid-critical-section",
                    node.lineno)
            self._visit_expr_tree(node.value, locks, txs)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, locks, txs)
            for child in ast.iter_child_nodes(node):
                self._visit_stmt(child, locks, txs)
            return
        for child in ast.iter_child_nodes(node):
            self._visit_stmt(child, locks, txs)

    def _visit_expr_tree(self, node, locks, txs) -> None:
        for child in ast.walk(node):
            if isinstance(child, ast.Call):
                self._visit_call(child, locks, txs)

    def _visit_call(self, call: ast.Call, locks: List[str],
                    txs: List[str]) -> None:
        if locks:
            wait = _is_wait(call)
            if wait is not None:
                self._emit(
                    "wait-under-lock", f"{wait}@{locks[-1]}",
                    f"cross-thread wait `{wait}` while holding lock "
                    f"{locks[-1]!r} (the PR 1 deadlock shape: the "
                    f"waited-on thread may need that lock)",
                    call.lineno)
            # Interprocedural lock-graph hop: callee acquires locks
            # while ours are held.
            d = dotted(call.func)
            if d is not None:
                callee = self.project.index.resolve(self.fn, d)
                if callee is not None:
                    for inner in _acquired_locks(callee):
                        for held in locks:
                            self._note_edge(held, inner, call.lineno)
        if txs:
            nested = _opens_nested_tx(call)
            if nested is not None:
                self._emit(
                    "nested-write-tx", f"{txs[-1]}>{nested}",
                    f"`{nested}(...)` opens its own write transaction "
                    f"inside an open `{txs[-1]}()` block — pass "
                    f"`conn=` instead", call.lineno)


def _acquired_locks(fn: FuncInfo) -> Set[str]:
    out: Set[str] = set()
    for node in own_body_walk(fn.node):
        if isinstance(node, ast.With):
            for item in node.items:
                ln = lock_name(item.context_expr)
                if ln is not None:
                    out.add(ln)
    return out


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS; each reported once, smallest-first
    rotation for stable idents."""
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, cur: str, path: List[str], seen: Set[str]):
        for nxt in sorted(edges.get(cur, ())):
            if nxt == start and len(path) > 1:
                i = path.index(min(path))
                cycles.add(tuple(path[i:] + path[:i]))
            elif nxt not in seen:
                seen.add(nxt)
                dfs(start, nxt, path + [nxt], seen)
                seen.discard(nxt)

    for node in sorted(edges):
        dfs(node, node, [node], {node})
    return [list(c) for c in sorted(cycles)]


class LockDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        edges: Dict[str, Set[str]] = {}
        edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for fn in project.index.funcs:
            _FnScanner(fn, project, edges, edge_sites, findings).scan()
            if fn.is_async:
                # Await nodes are caught in the walk; nothing extra.
                pass
        for cycle in _find_cycles(edges):
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            path, line = edge_sites.get(pairs[0], ("", 0))
            findings.append(Finding(
                PASS, "lock-order-cycle", path or "(project)", "",
                "<->".join(cycle),
                "lock-order cycle " + " -> ".join(cycle + [cycle[0]])
                + " — two threads taking these in opposite order "
                "deadlock", line))
        return findings
