"""Pass: telemetry — the PR 3 metric-namespace lint, folded into sdlint.

Semantics unchanged from the original `tools/telemetry_lint.py` (which
remains as a thin CLI shim over this module): every metric family must
be registered in `spacedrive_tpu/telemetry.py`, under a string-literal
name, collision-free, following `sd_<layer>_<what>` (layers now
include `sanitize`, the runtime sanitizer's counters). See the module
docstring of the shim for the rule-by-rule rationale.

Round 14 extends the same discipline to SPAN NAMES: a name passed to
`span()`/`device_span()` (tracing.py) is `<family>` or
`<family>/<variant>`, and the family must be declared via
`declare_span()` at the bottom of spacedrive_tpu/tracing.py — the
observable-name contract metric families already have, applied to the
trace surface the flight recorder exports. Codes:

- ``span-undeclared`` — a literal (or constant f-string prefix) whose
  family is not declared centrally;
- ``span-dynamic``    — a name with no resolvable constant family
  (bare variable, f-string with no `family/` prefix): an unauditable
  span namespace;
- ``span-central``    — a `declare_span()` call outside tracing.py.

Round 15 extends it to the HEALTH ENGINE's read surface: the
saturation engine (spacedrive_tpu/health.py) may only read metric
families listed in its module-bottom `READS` table, and every listed
family must be centrally registered — so the observatory can never
silently depend on a family that was renamed or removed. Codes:

- ``health-read-undeclared`` — a READS key that is not registered in
  spacedrive_tpu/telemetry.py;
- ``health-read-unlisted``   — a `sd_*` string literal in health.py
  outside the READS table (and not one of its own emitted
  `sd_health_*` families).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Set, Tuple

from ..core import Finding, Project, dotted

PASS = "telemetry"

FACTORY_NAMES = {"counter", "gauge", "histogram"}
CLASS_NAMES = {"Counter", "Gauge", "Histogram"}
NAME_RE = re.compile(
    r"^sd_(jobs?|identifier|sync|p2p|store|api|trace|sanitize|jit"
    r"|task|timeout|chan|pipeline|stage|race|health|sql|fleet|obs"
    r"|chaos|backoff|incident|persist|wire)"
    r"_[a-z0-9_]+$")

CENTRAL_MODULE = "telemetry.py"

SPAN_FUNCS = {"span", "device_span"}
SPAN_CENTRAL = "spacedrive_tpu/tracing.py"

HEALTH_MODULE = "health.py"


def health_reads_from_tree(tree: ast.Module) -> dict:
    """READS keys (family → key lineno) plus the key-node id set, from
    a parsed health.py: the module-level ``READS`` dict literal
    (plain or annotated assignment)."""
    reads: dict = {}
    key_ids: Set[int] = set()
    for node in tree.body:
        tgt = val = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt, val = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            tgt, val = node.target.id, node.value
        if tgt == "READS" and isinstance(val, ast.Dict):
            for k in val.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    reads[k.value] = k.lineno
                    key_ids.add(id(k))
    return {"reads": reads, "key_ids": key_ids}


def health_reads(root: str) -> dict:
    """The READS table parsed from spacedrive_tpu/health.py (family →
    lineno) — the static half of the runtime parity test."""
    path = os.path.join(root, "spacedrive_tpu", HEALTH_MODULE)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return {}
    return health_reads_from_tree(tree)["reads"]


def health_problems(path: str, tree: ast.Module,
                    declared_families: Set[str]
                    ) -> List[Tuple[int, str, str, str]]:
    """The health-engine read-surface checks over a parsed health.py:
    (lineno, code, ident, msg) tuples."""
    parsed = health_reads_from_tree(tree)
    reads, key_ids = parsed["reads"], parsed["key_ids"]
    out: List[Tuple[int, str, str, str]] = []
    for fam, lineno in sorted(reads.items()):
        if fam not in declared_families:
            out.append((
                lineno, "health-read-undeclared", fam,
                f"health engine READS entry {fam!r} is not registered "
                "in spacedrive_tpu/telemetry.py"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith("sd_") and id(node) not in key_ids:
            if node.value in reads or node.value.startswith("sd_health_"):
                continue
            out.append((
                node.lineno, "health-read-unlisted", node.value,
                f"sd_* literal {node.value!r} outside the READS table "
                "— every family the health engine reads must be "
                "listed there (spacedrive_tpu/health.py bottom)"))
    return out


def declared_span_families(root: str) -> Set[str]:
    """Family names from `declare_span("...")` calls in tracing.py."""
    path = os.path.join(root, SPAN_CENTRAL)
    out: Set[str] = set()
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name == "declare_span":
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    out.add(arg.value)
    return out


def _span_imports(tree: ast.Module) -> Tuple[dict, Set[str]]:
    """(function aliases, module aliases) for the tracing span
    surface: `from ..tracing import span as trace_span` binds a
    FUNCTION alias; `import spacedrive_tpu.tracing as tr` (or
    `from spacedrive_tpu import tracing`) binds a MODULE alias whose
    `.span(...)` calls must be checked too — the aliased-module
    spelling was the review-round bypass."""
    funcs: dict = {}
    modules: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[-1] == "tracing":
                for alias in node.names:
                    if alias.name in SPAN_FUNCS | {"declare_span"}:
                        funcs[alias.asname or alias.name] = alias.name
            # `from spacedrive_tpu import tracing [as tr]` AND the
            # pure-relative `from .. import tracing [as tr]` (where
            # node.module is None) both bind a module alias.
            for alias in node.names:
                if alias.name == "tracing":
                    modules.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[-1] == "tracing":
                    modules.add(alias.asname or alias.name)
    return funcs, modules


def _call_target(node: ast.Call) -> Tuple[str, str]:
    """(base, attr) of the called thing: ("", "counter") for a bare
    name, ("telemetry", "counter") for an attribute call."""
    f = node.func
    if isinstance(f, ast.Name):
        return "", f.id
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else "?"
        return base, f.attr
    return "?", "?"


def _telemetry_imports(tree: ast.Module) -> set:
    """Factory/class names this module imported FROM the telemetry
    module — a bare `counter(...)` call is only a registration if the
    name actually came from there (crypto code has an unrelated local
    `counter()` closure, for instance)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".")[-1] == "telemetry":
            for alias in node.names:
                if alias.name in FACTORY_NAMES | CLASS_NAMES:
                    names.add(alias.asname or alias.name)
    return names


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, is_central: bool, from_telemetry: set,
                 names_seen: dict, problems: List[str],
                 span_aliases: dict = None, span_families: Set[str] = None,
                 is_span_central: bool = False,
                 span_problems: List[Tuple[int, str, str, str]] = None,
                 span_modules: Set[str] = None):
        self.path = path
        self.is_central = is_central
        self.from_telemetry = from_telemetry
        self.names_seen = names_seen
        self.problems = problems
        self.span_aliases = span_aliases or {}
        self.span_modules = span_modules or set()
        self.span_families = span_families if span_families is not None \
            else set()
        self.is_span_central = is_span_central
        self.span_problems = span_problems if span_problems is not None \
            else []
        self.depth = 0  # function nesting (0 = module level)

    def visit_FunctionDef(self, node):
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- span-name discipline ----------------------------------------------

    def _span_call_target(self, node: ast.Call):
        """The original tracing function name this call binds to, or
        None when it is not a span-surface call. Covers bare/renamed
        function imports AND every module spelling — `tracing.span`,
        `tr.span` (aliased import), `spacedrive_tpu.tracing.span`
        (fully dotted)."""
        base, attr = _call_target(node)
        if base == "" and attr in self.span_aliases:
            return self.span_aliases[attr]
        if attr in SPAN_FUNCS | {"declare_span"}:
            d = dotted(node.func)
            if d is not None and "." in d:
                mod = d.rsplit(".", 1)[0]
                if mod == "tracing" or mod.endswith(".tracing") \
                        or mod in self.span_modules:
                    return attr
        return None

    def _check_span_call(self, node: ast.Call) -> None:
        target = self._span_call_target(node)
        if target is None or self.is_span_central:
            return
        if target == "declare_span":
            self.span_problems.append((
                node.lineno, "span-central",
                "declare_span",
                "span family declared outside the central registry "
                "(declare it in spacedrive_tpu/tracing.py)"))
            return
        if not node.args:
            self.span_problems.append((
                node.lineno, "span-dynamic", target,
                f"{target}() without a positional name literal — span "
                "names must start with a declared family"))
            return
        name_node = node.args[0]
        if isinstance(name_node, ast.Constant) and \
                isinstance(name_node.value, str):
            family = name_node.value.split("/", 1)[0]
            if family not in self.span_families:
                self.span_problems.append((
                    node.lineno, "span-undeclared", name_node.value,
                    f"span family {family!r} is not declared via "
                    "declare_span() in spacedrive_tpu/tracing.py"))
            return
        if isinstance(name_node, ast.JoinedStr):
            first = name_node.values[0] if name_node.values else None
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str) and "/" in first.value:
                family = first.value.split("/", 1)[0]
                if family not in self.span_families:
                    self.span_problems.append((
                        node.lineno, "span-undeclared",
                        f"{family}/<dynamic>",
                        f"span family {family!r} is not declared via "
                        "declare_span() in spacedrive_tpu/tracing.py"))
                return
            self.span_problems.append((
                node.lineno, "span-dynamic", target,
                "f-string span name with no constant `family/` prefix "
                "— the variant may be dynamic, the family may not"))
            return
        self.span_problems.append((
            node.lineno, "span-dynamic", target,
            "non-literal span name — span names must be `family` or "
            "`family/<variant>` with a declared, greppable family"))

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        self._check_span_call(node)
        base, attr = _call_target(node)
        qualified = base in ("telemetry", "REGISTRY")
        is_factory = attr in FACTORY_NAMES and (
            qualified or (base == "" and (
                attr in self.from_telemetry or self.is_central)))
        is_class = attr in CLASS_NAMES and (
            base == "telemetry"
            or (base == "" and attr in self.from_telemetry))
        if not (is_factory or is_class):
            return
        where = f"{self.path}:{node.lineno}"
        if not self.is_central:
            kind = "instantiated" if is_class else "registered"
            self.problems.append(
                f"{where}: metric family {kind} outside the central "
                f"registry (define it in spacedrive_tpu/telemetry.py "
                f"and import it)")
            return
        if self.depth > 0:
            return  # telemetry.py plumbing (wrapper/registry bodies)
        if not node.args:
            return
        name_node = node.args[0]
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            self.problems.append(
                f"{where}: metric name must be a string literal "
                f"(static namespace)")
            return
        name = name_node.value
        if name in self.names_seen:
            self.problems.append(
                f"{where}: metric name collision: {name!r} already "
                f"registered at {self.names_seen[name]}")
        else:
            self.names_seen[name] = where
        if not NAME_RE.match(name):
            self.problems.append(
                f"{where}: {name!r} breaks the naming scheme "
                f"sd_<layer>_<what> (layers: jobs/identifier/sync/"
                f"p2p/store/api/trace/sanitize/jit/task/timeout/chan/"
                f"pipeline/stage/race/health/sql/fleet/obs/chaos/"
                f"backoff/incident/persist/wire)")


def lint_source(path: str, src: str, is_central: bool,
                names_seen: dict, problems: List[str],
                span_families: Set[str] = None,
                is_span_central: bool = False,
                span_problems: List[Tuple[int, str, str, str]] = None
                ) -> None:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        problems.append(f"{path}: unparseable: {e}")
        return
    span_aliases, span_modules = _span_imports(tree)
    _Visitor(path, is_central, _telemetry_imports(tree),
             names_seen, problems,
             span_aliases=span_aliases,
             span_modules=span_modules,
             span_families=span_families,
             is_span_central=is_span_central,
             span_problems=span_problems).visit(tree)


def run_lint(package_dir: str) -> List[str]:
    """Lint every .py under package_dir; returns problem strings.
    (The telemetry_lint.py shim's public API — kept verbatim; span
    problems land in the same string list.)"""
    problems: List[str] = []
    names_seen: dict = {}
    span_families = declared_span_families(os.path.dirname(
        os.path.abspath(package_dir)))
    # Central module first so cross-file collisions blame the outlier.
    paths: List[str] = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fn in sorted(files):
            if fn.endswith(".py"):
                paths.append(os.path.join(root, fn))
    paths.sort(key=lambda p: (os.path.basename(p) != CENTRAL_MODULE, p))
    for path in paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        span_problems: List[Tuple[int, str, str, str]] = []
        lint_source(path, src,
                    is_central=os.path.basename(path) == CENTRAL_MODULE,
                    names_seen=names_seen, problems=problems,
                    span_families=span_families,
                    is_span_central=path.replace(os.sep, "/").endswith(
                        SPAN_CENTRAL),
                    span_problems=span_problems)
        for lineno, _code, _ident, msg in span_problems:
            problems.append(f"{path}:{lineno}: {msg}")
    # Health-engine read surface (needs the full declared-name set,
    # so it runs after the walk).
    for path in paths:
        if os.path.basename(path) != HEALTH_MODULE:
            continue
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except (OSError, SyntaxError):
            continue
        for lineno, _code, _ident, msg in health_problems(
                path, tree, set(names_seen)):
            problems.append(f"{path}:{lineno}: {msg}")
    return problems


def main(argv: List[str]) -> int:
    pkg = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "..", "spacedrive_tpu")
    pkg = os.path.normpath(pkg)
    problems = run_lint(pkg)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"telemetry lint: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print("telemetry lint: clean")
    return 0


_LINE_RE = re.compile(r"^(?P<path>.*?):(?P<line>\d+): (?P<msg>.*)$")


class TelemetryPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        problems: List[str] = []
        names_seen: dict = {}
        span_families = declared_span_families(project.root)
        files = sorted(
            project.files,
            key=lambda f: (os.path.basename(f.relpath) != CENTRAL_MODULE,
                           f.relpath))
        findings: List[Finding] = []
        for src in files:
            span_problems: List[Tuple[int, str, str, str]] = []
            lint_source(
                src.relpath, src.src,
                is_central=os.path.basename(src.relpath) == CENTRAL_MODULE,
                names_seen=names_seen, problems=problems,
                span_families=span_families,
                is_span_central=src.relpath == SPAN_CENTRAL,
                span_problems=span_problems)
            for lineno, code, ident, msg in span_problems:
                findings.append(Finding(
                    PASS, code, src.relpath, "", ident, msg, lineno))
        for src in files:
            if os.path.basename(src.relpath) != HEALTH_MODULE:
                continue
            for lineno, code, ident, msg in health_problems(
                    src.relpath, src.tree, set(names_seen)):
                findings.append(Finding(
                    PASS, code, src.relpath, "", ident, msg, lineno))
        for prob in problems:
            m = _LINE_RE.match(prob)
            if m:
                findings.append(Finding(
                    PASS, "namespace", m.group("path"), "",
                    m.group("msg")[:80], m.group("msg"),
                    int(m.group("line"))))
            else:
                findings.append(Finding(
                    PASS, "namespace", prob.split(":", 1)[0], "",
                    prob[:80], prob, 0))
        return findings
