"""Pass: telemetry — the PR 3 metric-namespace lint, folded into sdlint.

Semantics unchanged from the original `tools/telemetry_lint.py` (which
remains as a thin CLI shim over this module): every metric family must
be registered in `spacedrive_tpu/telemetry.py`, under a string-literal
name, collision-free, following `sd_<layer>_<what>` (layers now
include `sanitize`, the runtime sanitizer's counters). See the module
docstring of the shim for the rule-by-rule rationale.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Tuple

from ..core import Finding, Project

PASS = "telemetry"

FACTORY_NAMES = {"counter", "gauge", "histogram"}
CLASS_NAMES = {"Counter", "Gauge", "Histogram"}
NAME_RE = re.compile(
    r"^sd_(jobs?|identifier|sync|p2p|store|api|trace|sanitize|jit"
    r"|task|timeout|chan|pipeline|stage|race)_[a-z0-9_]+$")

CENTRAL_MODULE = "telemetry.py"


def _call_target(node: ast.Call) -> Tuple[str, str]:
    """(base, attr) of the called thing: ("", "counter") for a bare
    name, ("telemetry", "counter") for an attribute call."""
    f = node.func
    if isinstance(f, ast.Name):
        return "", f.id
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else "?"
        return base, f.attr
    return "?", "?"


def _telemetry_imports(tree: ast.Module) -> set:
    """Factory/class names this module imported FROM the telemetry
    module — a bare `counter(...)` call is only a registration if the
    name actually came from there (crypto code has an unrelated local
    `counter()` closure, for instance)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".")[-1] == "telemetry":
            for alias in node.names:
                if alias.name in FACTORY_NAMES | CLASS_NAMES:
                    names.add(alias.asname or alias.name)
    return names


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, is_central: bool, from_telemetry: set,
                 names_seen: dict, problems: List[str]):
        self.path = path
        self.is_central = is_central
        self.from_telemetry = from_telemetry
        self.names_seen = names_seen
        self.problems = problems
        self.depth = 0  # function nesting (0 = module level)

    def visit_FunctionDef(self, node):
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        base, attr = _call_target(node)
        qualified = base in ("telemetry", "REGISTRY")
        is_factory = attr in FACTORY_NAMES and (
            qualified or (base == "" and (
                attr in self.from_telemetry or self.is_central)))
        is_class = attr in CLASS_NAMES and (
            base == "telemetry"
            or (base == "" and attr in self.from_telemetry))
        if not (is_factory or is_class):
            return
        where = f"{self.path}:{node.lineno}"
        if not self.is_central:
            kind = "instantiated" if is_class else "registered"
            self.problems.append(
                f"{where}: metric family {kind} outside the central "
                f"registry (define it in spacedrive_tpu/telemetry.py "
                f"and import it)")
            return
        if self.depth > 0:
            return  # telemetry.py plumbing (wrapper/registry bodies)
        if not node.args:
            return
        name_node = node.args[0]
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            self.problems.append(
                f"{where}: metric name must be a string literal "
                f"(static namespace)")
            return
        name = name_node.value
        if name in self.names_seen:
            self.problems.append(
                f"{where}: metric name collision: {name!r} already "
                f"registered at {self.names_seen[name]}")
        else:
            self.names_seen[name] = where
        if not NAME_RE.match(name):
            self.problems.append(
                f"{where}: {name!r} breaks the naming scheme "
                f"sd_<layer>_<what> (layers: jobs/identifier/sync/"
                f"p2p/store/api/trace/sanitize/jit/task/timeout/chan/"
                f"pipeline/stage/race)")


def lint_source(path: str, src: str, is_central: bool,
                names_seen: dict, problems: List[str]) -> None:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        problems.append(f"{path}: unparseable: {e}")
        return
    _Visitor(path, is_central, _telemetry_imports(tree),
             names_seen, problems).visit(tree)


def run_lint(package_dir: str) -> List[str]:
    """Lint every .py under package_dir; returns problem strings.
    (The telemetry_lint.py shim's public API — kept verbatim.)"""
    problems: List[str] = []
    names_seen: dict = {}
    # Central module first so cross-file collisions blame the outlier.
    paths: List[str] = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fn in sorted(files):
            if fn.endswith(".py"):
                paths.append(os.path.join(root, fn))
    paths.sort(key=lambda p: (os.path.basename(p) != CENTRAL_MODULE, p))
    for path in paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        lint_source(path, src,
                    is_central=os.path.basename(path) == CENTRAL_MODULE,
                    names_seen=names_seen, problems=problems)
    return problems


def main(argv: List[str]) -> int:
    pkg = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "..", "spacedrive_tpu")
    pkg = os.path.normpath(pkg)
    problems = run_lint(pkg)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"telemetry lint: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print("telemetry lint: clean")
    return 0


_LINE_RE = re.compile(r"^(?P<path>.*?):(?P<line>\d+): (?P<msg>.*)$")


class TelemetryPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        problems: List[str] = []
        names_seen: dict = {}
        files = sorted(
            project.files,
            key=lambda f: (os.path.basename(f.relpath) != CENTRAL_MODULE,
                           f.relpath))
        for src in files:
            lint_source(
                src.relpath, src.src,
                is_central=os.path.basename(src.relpath) == CENTRAL_MODULE,
                names_seen=names_seen, problems=problems)
        findings: List[Finding] = []
        for prob in problems:
            m = _LINE_RE.match(prob)
            if m:
                findings.append(Finding(
                    PASS, "namespace", m.group("path"), "",
                    m.group("msg")[:80], m.group("msg"),
                    int(m.group("line"))))
            else:
                findings.append(Finding(
                    PASS, "namespace", prob.split(":", 1)[0], "",
                    prob[:80], prob, 0))
        return findings
