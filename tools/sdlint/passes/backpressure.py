"""Pass: backpressure — every producer handles `full`.

A bounded channel only helps if its producers do something sane at
the bound: await a BUDGETED put (block policy — the wait is the
backpressure), or shed/coalesce by declared policy. The failure
shapes this pass encodes are the ones the registry adoption killed:
a `put_nowait` straight into a block-policy channel (silently
reintroducing the unbounded-or-crash choice), a fan-out loop
appending to per-subscriber buffers no bound ever touches (the
pre-registry ws emit path), and a `send_nowait` burst with no drain
point in the loop (a wedged peer then buffers the whole stream in
the transport).

Codes:

- ``block-without-budget`` — a declared block-policy queue contract
  whose `put_budget` is missing or not a declared timeouts.py name
  (checked against both registries' AST; `declare_channel` also
  raises at import, but the build must fail without importing).
- ``nowait-on-block`` — `put_nowait` on an attribute constructed from
  a block-policy registry channel: the producer must use the
  budgeted `await put()` (ChannelFull at runtime is the sanitizer
  twin of this finding).
- ``unbounded-fanout`` — inside a `for`/`async for`, an
  `append`/`put_nowait` onto a receiver rooted at the LOOP VARIABLE
  (a per-subscriber/per-peer buffer written once per fan-out round):
  nothing bounds what one slow subscriber accumulates — route the
  fan-out through a registered channel per subscriber.
- ``burst-without-drain`` — a loop body issuing `send_nowait` with no
  awaited drain/flush or budgeted wait anywhere in the same loop:
  bursts must close their window (sync_net's CLONE_WINDOW drain is
  the sanctioned shape, and proto's frame Window enforces the cap at
  runtime).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import Finding, Project, SourceFile, dotted, own_body_walk
from .queue_discipline import CENTRAL, declared_channels
from .timeout_discipline import declared_timeouts

PASS = "backpressure"

_DRAIN_LAST = {"drain", "flush", "with_timeout", "wait_for", "put",
               "get", "recv"}


def _registered_block_attrs(cls: ast.ClassDef,
                            declared: Dict[str, Dict]) -> Set[str]:
    """Attrs of `cls` assigned from channels.channel("<name>") where
    <name> is a declared block-policy queue."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        d = dotted(node.value.func)
        if d is None or d.rsplit(".", 1)[-1] != "channel":
            continue
        args = node.value.args
        if not (args and isinstance(args[0], ast.Constant)
                and isinstance(args[0].value, str)):
            continue
        spec = declared.get(args[0].value)
        if spec is None or spec.get("policy") != "block":
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                out.add(tgt.attr)
    return out


class BackpressurePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_channels(project.root)
        timeouts = declared_timeouts(project.root)
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        # Contract-level rule: block queues must carry a real budget.
        for name, spec in sorted(declared.items()):
            if spec.get("policy") != "block" or \
                    spec.get("kind") != "queue":
                continue
            budget = spec.get("put_budget")
            if not budget or budget not in timeouts:
                emit(Finding(
                    PASS, "block-without-budget", CENTRAL, "", name,
                    f"block-policy channel {name!r} needs put_budget "
                    "naming a declared timeouts.py budget (producers "
                    "must never wait unbounded)",
                    spec.get("lineno", 0)))

        for src in project.files:
            if src.relpath == CENTRAL:
                continue
            self._check_file(src, declared, emit)
        return findings

    def _check_file(self, src: SourceFile, declared: Dict, emit) -> None:
        block_attrs_by_cls: Dict[str, Set[str]] = {}
        fn_cls: Dict[int, str] = {}  # id(fn node) → class name, one sweep
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                block_attrs_by_cls[node.name] = _registered_block_attrs(
                    node, declared)
                for child in node.body:
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        fn_cls[id(child)] = node.name
        for fn in [f for f in ast.walk(src.tree)
                   if isinstance(f, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            cls = fn_cls.get(id(fn))
            qual = f"{cls}.{fn.name}" if cls else fn.name
            self._check_fn(src, fn, qual,
                           block_attrs_by_cls.get(cls or "", set()),
                           emit)

    def _check_fn(self, src: SourceFile, fn, qual: str,
                  block_attrs: Set[str], emit) -> None:
        rel = src.relpath
        for node in own_body_walk(fn):
            # nowait-on-block
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None:
                    parts = d.split(".")
                    if parts[-1] == "put_nowait" and len(parts) == 3 \
                            and parts[0] == "self" and \
                            parts[1] in block_attrs:
                        emit(Finding(
                            PASS, "nowait-on-block", rel, qual,
                            f"self.{parts[1]}.put_nowait",
                            f"put_nowait on block-policy channel "
                            f"`self.{parts[1]}`: use the budgeted "
                            "`await put()` — full must mean "
                            "backpressure, not ChannelFull",
                            node.lineno))
            # loop-scoped rules
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            self._check_loop(src, node, qual, emit)

    def _loop_subtree(self, loop: ast.AST):
        """The loop's body/orelse, not descending into nested defs."""
        stack = list(loop.body) + list(getattr(loop, "orelse", []))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _check_loop(self, src: SourceFile, loop: ast.AST, qual: str,
                    emit) -> None:
        rel = src.relpath
        target_names: Set[str] = set()
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(loop.target):
                if isinstance(sub, ast.Name):
                    target_names.add(sub.id)
        sends: List[ast.Call] = []
        has_drain_await = False
        for n in self._loop_subtree(loop):
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call):
                d = dotted(n.value.func)
                if d is not None and \
                        d.rsplit(".", 1)[-1] in _DRAIN_LAST:
                    has_drain_await = True
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            if d is None:
                continue
            parts = d.split(".")
            last = parts[-1]
            if last == "send_nowait":
                sends.append(n)
            if last in ("append", "put_nowait") and len(parts) >= 2 \
                    and parts[0] in target_names:
                emit(Finding(
                    PASS, "unbounded-fanout", rel, qual, d,
                    f"per-subscriber buffer write `{d}` inside a "
                    "fan-out loop with no bound: a slow subscriber "
                    "accumulates unbounded memory — deliver through a "
                    "registered bounded channel",
                    n.lineno))
        if sends and not has_drain_await:
            d = dotted(sends[0].func) or "send_nowait"
            emit(Finding(
                PASS, "burst-without-drain", rel, qual, d,
                f"`{d}` burst inside a loop with no awaited "
                "drain/budgeted wait: the window never closes and "
                "a wedged receiver buffers the whole stream",
                sends[0].lineno))
