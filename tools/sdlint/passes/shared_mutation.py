"""Pass: shared-mutation — every mutable attribute of a multi-context
class obeys its declared ownership contract.

PR 8's review rounds were spent hand-fixing exactly this bug class:
`PipelineStats` plain `+=` from two device streams lost updates, and
the stage-pool gauge clobbered across a concurrent pool swap. The
contract table lives in `spacedrive_tpu/threadctx.py` (one
`declare_owner(...)` per class, one kind per mutable attribute); this
pass derives thread contexts from the call graph (`_threads.py`:
event loop, per-submission worker roots, atexit) and checks every
attribute-mutation site against the table — the lockset half reuses
the PR 4 lock-discipline lexical machinery.

Codes:

- ``unguarded-write``     — a post-init write to a `guarded_by(L)`
  attribute outside a lexical `with <L>:` block (the encoded
  `PipelineStats.h2d_bytes` `+=` shape).
- ``wrong-context-write`` — a `loop_only` attribute written from a
  function reachable from a worker/atexit context.
- ``multi-thread-write``  — a `single_thread` attribute whose mutation
  sites span two or more distinct thread contexts.
- ``non-atomic-write``    — an `atomic_counter` attribute mutated by
  anything other than an augmented numeric update (the declaration
  waives bare `+=` statistics, nothing stronger).
- ``post-init-write``     — an `immutable_after_init` attribute
  written outside `__init__`/`__post_init__`.
- ``undeclared-attr``     — a post-init mutation of an attribute the
  class's contract does not name (contracts must stay complete, or
  they rot).
- ``undeclared-class``    — attribute mutations of an UNregistered
  class spanning two or more thread contexts: declare it in
  threadctx.py (or serialize it onto one context).

The runtime twin (`threadctx.arm`, installed with the sanitizer)
covers the dynamic-dispatch half: armed classes record (thread id,
held lockset) per write and raise `data_race` in tier-1.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Finding, Project
from ._threads import (
    MutationSite,
    class_hierarchy,
    collect_mutations,
    declared_owners,
    effective_owner,
    owners_by_class,
    thread_contexts,
)

PASS = "shared-mutation"


def _class_def_lines(project: Project) -> Dict[Tuple[str, str], int]:
    out: Dict[Tuple[str, str], int] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                out[(src.relpath, node.name)] = node.lineno
    return out


class SharedMutationPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_owners(project.root, project)
        by_class = owners_by_class(declared)
        hierarchy = class_hierarchy(project)
        contexts = thread_contexts(project)
        known = set(by_class)
        sites = collect_mutations(project, known)
        # Contract lookup follows inheritance (Gauge under Counter),
        # memoized per class name.
        owner_of: Dict[str, object] = {}

        def owner(cls_name: str):
            if cls_name not in owner_of:
                owner_of[cls_name] = effective_owner(
                    cls_name, by_class, hierarchy)
            return owner_of[cls_name]
        def_lines = _class_def_lines(project)
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        def ctx_of(site: MutationSite) -> Set[str]:
            return contexts.get(
                f"{site.fn.src.relpath}::{site.fn.qual}", set())

        # -- registered classes: contract enforcement ----------------------
        by_attr: Dict[Tuple[str, str], List[MutationSite]] = {}
        for s in sites:
            if owner(s.cls_name) is not None and not s.in_init \
                    and not s.attr.startswith("_sdtpu"):
                by_attr.setdefault((s.cls_name, s.attr), []).append(s)

        for (cls_name, attr), group in sorted(by_attr.items()):
            spec = owner(cls_name)
            contract = spec["attrs"].get(attr)
            first = min(group, key=lambda s: (s.fn.src.relpath,
                                              s.lineno))
            if contract is None:
                emit(Finding(
                    PASS, "undeclared-attr", first.fn.src.relpath,
                    first.fn.qual, f"{cls_name}.{attr}",
                    f"`{cls_name}.{attr}` is mutated outside __init__ "
                    f"but the owner contract {spec['name']!r} declares "
                    "no kind for it — add loop_only / single_thread / "
                    "guarded_by / atomic_counter / "
                    "immutable_after_init in threadctx.py",
                    first.lineno))
                continue
            kind, lock = contract
            if kind == "guarded_by":
                # Lexical lock identity is the terminal attr name (the
                # lock-discipline convention): guarded_by supports a
                # dotted runtime path ("db._write_lock").
                lock_term = (lock or "").split(".")[-1]
                for s in group:
                    if lock_term not in s.locks:
                        emit(Finding(
                            PASS, "unguarded-write", s.fn.src.relpath,
                            s.fn.qual, f"{cls_name}.{attr}",
                            f"`{cls_name}.{attr}` is declared "
                            f"guarded_by({lock!r}) but this "
                            + ("augmented update"
                               if s.aug else "write")
                            + f" holds {sorted(s.locks) or 'no lock'}"
                            " — a concurrent writer loses updates "
                            "(the PR 8 PipelineStats shape)",
                            s.lineno))
            elif kind == "loop_only":
                for s in group:
                    bad = {c for c in ctx_of(s) if c != "loop"}
                    if bad:
                        emit(Finding(
                            PASS, "wrong-context-write",
                            s.fn.src.relpath, s.fn.qual,
                            f"{cls_name}.{attr}",
                            f"`{cls_name}.{attr}` is declared "
                            f"loop_only but `{s.fn.qual}` is reachable "
                            f"from {sorted(bad)} — post through "
                            "threadctx.call_threadsafe or re-declare",
                            s.lineno))
            elif kind == "single_thread":
                labels: Set[str] = set()
                for s in group:
                    labels |= ctx_of(s)
                if len(labels) >= 2:
                    emit(Finding(
                        PASS, "multi-thread-write",
                        first.fn.src.relpath, first.fn.qual,
                        f"{cls_name}.{attr}",
                        f"`{cls_name}.{attr}` is declared "
                        f"single_thread but its writers span contexts "
                        f"{sorted(labels)} — guard it or serialize "
                        "the writers",
                        first.lineno))
            elif kind == "atomic_counter":
                for s in group:
                    if not s.aug or s.container:
                        emit(Finding(
                            PASS, "non-atomic-write", s.fn.src.relpath,
                            s.fn.qual, f"{cls_name}.{attr}",
                            f"`{cls_name}.{attr}` is declared "
                            "atomic_counter: only bare augmented "
                            "numeric updates are waived — this "
                            + ("container mutation" if s.container
                               else "rebind")
                            + " needs a real contract",
                            s.lineno))
            elif kind == "immutable_after_init":
                for s in group:
                    emit(Finding(
                        PASS, "post-init-write", s.fn.src.relpath,
                        s.fn.qual, f"{cls_name}.{attr}",
                        f"`{cls_name}.{attr}` is declared "
                        "immutable_after_init but is written outside "
                        "__init__",
                        s.lineno))

        # -- unregistered classes: multi-context detection ------------------
        grouped: Dict[Tuple[str, str], List[MutationSite]] = {}
        for s in sites:
            if owner(s.cls_name) is not None or not s.self_recv \
                    or s.in_init:
                continue
            grouped.setdefault(
                (s.fn.src.relpath, s.cls_name), []).append(s)
        for (relpath, cls_name), group in sorted(grouped.items()):
            labels = set()
            for s in group:
                labels |= ctx_of(s)
            if len(labels) < 2:
                continue
            attrs = sorted({s.attr for s in group})
            emit(Finding(
                PASS, "undeclared-class", relpath, "", cls_name,
                f"class `{cls_name}` mutates {attrs} from contexts "
                f"{sorted(labels)} without an ownership contract — "
                "declare it in spacedrive_tpu/threadctx.py "
                "(declare_owner) so the race recorder can arm it",
                def_lines.get((relpath, cls_name),
                              group[0].lineno)))
        return findings
