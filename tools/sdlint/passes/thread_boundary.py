"""Pass: thread-boundary — loop-affine calls stay on the loop.

asyncio primitives are not thread-safe: `create_task`, waking a
channel's waiter futures (`Channel.put_nowait` → `fut.set_result`),
and EventBus fan-out all assume the event-loop thread. Code running on
an executor thread (a `to_thread` target, a staging-pool worker, a
per-device dispatch stream) must cross back through
`loop.call_soon_threadsafe(...)` / `asyncio.run_coroutine_threadsafe`
— or this tree's hardened spelling, `threadctx.call_threadsafe(loop,
cb, *args)`, which additionally tolerates a loop closed mid-shutdown
(the raw idioms at the old p2p/sync_net originate_soon and api/server
ws-emit sites are the sanctioned shapes this pass points at).

Codes:

- ``loop-call-from-thread`` — a loop-affine call (task spawn, channel
  method, EventBus emit) in a function reachable from a worker/atexit
  context, not wrapped in a threadsafe poster. A function reachable
  from BOTH loop and worker contexts is flagged too: in its worker
  incarnation the call corrupts loop state.
- ``raw-threadsafe-handoff`` — a literal `loop.call_soon_threadsafe`
  / `run_coroutine_threadsafe` call outside threadctx.py: the raw
  primitive crashes the posting thread with `RuntimeError: Event loop
  is closed` when shutdown wins the race — use
  `threadctx.call_threadsafe`, which swallows exactly that shape and
  counts it into `sd_race_handoff_closed_total`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk
from ._threads import CENTRAL, thread_contexts

PASS = "thread-boundary"

# Channel-typed receivers: methods that touch waiter futures or the
# slot deque — loop-affine even on the "pure sync" surface once any
# async consumer is parked.
_CHANNEL_METHODS = {"put", "put_nowait", "get", "get_nowait", "remove",
                    "popleft", "note_put", "note_drain"}
_CHANNEL_FACTORIES = {"channel", "window", "bounded_dict"}

# Task-spawn shapes (the supervisor resolves through the project
# index; the asyncio spellings are matched by name).
_SPAWN_DOTTED = {"asyncio.create_task", "asyncio.ensure_future",
                 "tasks.spawn"}

# EventBus receivers by naming idiom (node.py: `self.events.emit`).
_BUS_RECEIVERS = {"events", "bus", "event_bus"}

_RAW_POSTERS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}


def _channel_attrs(src_tree: ast.Module) -> Dict[str, Set[str]]:
    """class name → self-attrs assigned from channels.channel/window/
    bounded_dict (the queue-discipline registration idiom)."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(src_tree):
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: Set[str] = set()
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            d = dotted(sub.value.func)
            if d is None or \
                    d.rsplit(".", 1)[-1] not in _CHANNEL_FACTORIES:
                continue
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    attrs.add(tgt.attr)
        if attrs:
            out[node.name] = attrs
    return out


def _local_channels(fn: FuncInfo) -> Set[str]:
    out: Set[str] = set()
    for node in own_body_walk(fn.node):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        d = dotted(node.value.func)
        if d is None or d.rsplit(".", 1)[-1] not in _CHANNEL_FACTORIES:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


def _loop_affine(call: ast.Call, fn: FuncInfo, project: Project,
                 chan_attrs: Dict[str, Set[str]],
                 local_chans: Set[str]) -> Optional[str]:
    """Stable ident when this call is loop-affine, else None."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    if d in _SPAWN_DOTTED:
        return d
    if last == "spawn":
        callee = project.index.resolve(fn, d)
        if callee is not None and \
                callee.src.relpath.endswith("tasks.py"):
            return d
    if last in _CHANNEL_METHODS and len(parts) >= 2:
        recv = parts[:-1]
        if recv[0] == "self" and len(recv) == 2 and fn.cls and \
                recv[1] in chan_attrs.get(fn.cls, set()):
            return d
        if len(recv) == 1 and recv[0] in local_chans:
            return d
    if last in ("emit", "publish") and len(parts) >= 2 and \
            parts[-2] in _BUS_RECEIVERS:
        return d
    return None


class ThreadBoundaryPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        contexts = thread_contexts(project)
        chan_attrs_by_file: Dict[str, Dict[str, Set[str]]] = {}
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            ctx = contexts.get(f"{fn.src.relpath}::{fn.qual}", set())
            off_loop = {c for c in ctx if c != "loop"}
            chan_attrs = chan_attrs_by_file.get(fn.src.relpath)
            if chan_attrs is None:
                chan_attrs = _channel_attrs(fn.src.tree)
                chan_attrs_by_file[fn.src.relpath] = chan_attrs
            local_chans = _local_channels(fn) if off_loop else set()
            for site in fn.calls:
                d = site.name
                last = d.rsplit(".", 1)[-1]
                if last in _RAW_POSTERS and \
                        fn.src.relpath != CENTRAL:
                    emit(Finding(
                        PASS, "raw-threadsafe-handoff",
                        fn.src.relpath, fn.qual, d,
                        f"raw `{d}` hand-off: a loop closed "
                        "mid-shutdown raises RuntimeError into the "
                        "posting thread — use "
                        "threadctx.call_threadsafe(loop, cb, *args)",
                        site.node.lineno))
                if not off_loop or site.wrapped:
                    continue
                ident = _loop_affine(site.node, fn, project,
                                     chan_attrs, local_chans)
                if ident is not None:
                    emit(Finding(
                        PASS, "loop-call-from-thread",
                        fn.src.relpath, fn.qual, ident,
                        f"loop-affine call `{ident}` in a function "
                        f"reachable from {sorted(off_loop)} — post it "
                        "through threadctx.call_threadsafe(loop, ...) "
                        "(asyncio primitives and registry channels "
                        "are not thread-safe)",
                        site.node.lineno))
        return findings
