"""Pass: unbounded-growth — long-lived components must evict.

A bounded channel is pointless next to an instance dict that gains a
key per event and never loses one: in a component that lives as long
as the node (an actor loop, a supervised spawner, a start/stop
service), a grow-only collection IS a memory leak with a workload
knob. This pass finds instance collections in long-lived classes —
and module-level collections in the engine package — that only ever
grow: `append`/`add`/`extend`/`[k] =`/`setdefault`/`update` somewhere,
with no `pop`/`popleft`/`popitem`/`remove`/`discard`/`clear`/`del`/
reassignment on ANY path in the same class (nested closures count:
an unsubscribe lambda is a legitimate eviction path).

Scope:

- **Long-lived classes** only: a class whose body contains a
  ``while True`` loop, spawns through the task supervisor
  (`tasks.spawn`), or defines both `start` and `stop` — the actor /
  service shapes. Request-scoped helpers may accumulate freely; their
  lifetime bounds them.
- **Module level** inside `spacedrive_tpu/` (CLIs under tools/ are
  single-shot; fixtures opt in with a ``# sdlint-scope: growth``
  head marker). The central declaration registries (flags, timeouts,
  channels, telemetry, the jit contract table) are exempt by path:
  their dicts are written once at import by design.
- **Registry-declared caches are exempt**: an attribute constructed
  through `channels.channel/window/bounded_dict(...)` carries its own
  declared bound, as does any `deque(maxlen=...)`.

Code: ``grow-only``, anchored at the collection's construction line so
an `# sdlint: ok[unbounded-growth]` marker (with its reason) sits next
to the declaration it waives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Project, SourceFile, dotted

PASS = "unbounded-growth"

SCOPE_PREFIX = "spacedrive_tpu/"
SCOPE_MARKER = "# sdlint-scope: growth"
# Central declaration registries: module dicts written at import time
# by design (the adoption passes themselves read them).
EXEMPT_MODULES = {
    "spacedrive_tpu/flags.py",
    "spacedrive_tpu/timeouts.py",
    "spacedrive_tpu/channels.py",
    "spacedrive_tpu/telemetry.py",
    "spacedrive_tpu/threadctx.py",
    "spacedrive_tpu/ops/jit_registry.py",
}

_GROW = {"append", "appendleft", "add", "extend", "insert",
         "setdefault", "update"}
_SHRINK = {"pop", "popleft", "popitem", "remove", "discard", "clear"}
_COLLECTION_CTORS = {"dict", "set", "list", "deque", "OrderedDict",
                     "defaultdict"}
_REGISTRY_CTORS = {"channel", "window", "bounded_dict"}


def _collection_ctor(value: ast.AST) -> Optional[str]:
    """'bounded' | 'registry' | 'plain' | None for an assigned value.
    A NON-EMPTY list literal is fixed-slot state (`[0, 0]` counters,
    build-time tables): subscript writes update it, they don't grow
    it — treated as bounded."""
    if isinstance(value, (ast.Dict, ast.Set)):
        return "plain"
    if isinstance(value, ast.List):
        return "bounded" if value.elts else "plain"
    if not isinstance(value, ast.Call):
        return None
    d = dotted(value.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    if last in _REGISTRY_CTORS:
        return "registry"
    if last not in _COLLECTION_CTORS:
        return None
    if last == "deque" and any(kw.arg == "maxlen"
                               for kw in value.keywords):
        return "bounded"
    return "plain"


def _root_attr(node: ast.AST) -> Optional[Tuple[str, str]]:
    """("self", "x") for `self.x`, ("", "x") for a bare name `x`."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name):
        return node.value.id, node.attr
    if isinstance(node, ast.Name):
        return "", node.id
    return None


class _Tracker:
    """Grow/shrink evidence for one namespace (a class's self-attrs,
    or a module's globals)."""

    def __init__(self):
        self.collections: Dict[str, Tuple[int, str]] = {}  # name → (line, kind)
        self.grown: Set[str] = set()
        self.shrunk: Set[str] = set()

    def note_assign(self, name: str, value: ast.AST, lineno: int,
                    is_init: bool) -> None:
        kind = _collection_ctor(value)
        if kind is not None:
            if name not in self.collections:
                self.collections[name] = (lineno, kind)
            elif not is_init:
                # reassignment elsewhere is a reset path
                self.shrunk.add(name)
        elif name in self.collections and not is_init:
            self.shrunk.add(name)

    def findings(self, rel: str, qual: str, emit) -> None:
        for name, (lineno, kind) in sorted(self.collections.items()):
            if kind in ("bounded", "registry"):
                continue
            if name in self.grown and name not in self.shrunk:
                where = f"self.{name}" if qual else name
                emit(Finding(
                    PASS, "grow-only", rel, qual, where,
                    f"collection `{where}` only grows (no eviction/"
                    "discard/maxlen on any path in this long-lived "
                    "component): bound it, evict it, or declare it a "
                    "registry cache (channels.bounded_dict)",
                    lineno))


def _scan(body_walker, tracker: _Tracker, attr_root: str) -> None:
    """Record grow/shrink ops on `attr_root`-rooted receivers
    (attr_root 'self' for classes, '' for module globals)."""
    for node in body_walker:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                # growth via subscript write: self.x[k] = v / x[k] = v
                if isinstance(tgt, ast.Subscript):
                    root = _root_attr(tgt.value)
                    if root is not None and root[0] == attr_root:
                        tracker.grown.add(root[1])
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    root = _root_attr(tgt.value)
                    if root is not None and root[0] == attr_root:
                        tracker.shrunk.add(root[1])
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) < 2:
                continue
            last = parts[-1]
            recv = parts[:-1]
            match = (attr_root == "self" and len(recv) == 2
                     and recv[0] == "self") or \
                    (attr_root == "" and len(recv) == 1)
            if not match:
                continue
            name = recv[-1]
            if last in _GROW:
                tracker.grown.add(name)
            elif last in _SHRINK:
                tracker.shrunk.add(name)


def _is_long_lived(cls: ast.ClassDef) -> bool:
    has_start = has_stop = False
    for node in ast.walk(cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "start":
                has_start = True
            if node.name == "stop":
                has_stop = True
        if isinstance(node, ast.While) and \
                isinstance(node.test, ast.Constant) and \
                node.test.value is True:
            return True
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is not None and d.rsplit(".", 1)[-1] == "spawn" and \
                    (d == "spawn" or d.endswith("tasks.spawn")):
                return True
    return has_start and has_stop


class UnboundedGrowthPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for src in project.files:
            head = "\n".join(src.lines[:5])
            in_scope = src.relpath.startswith(SCOPE_PREFIX) or \
                SCOPE_MARKER in head
            if not in_scope or src.relpath in EXEMPT_MODULES:
                continue
            self._check_module(src, emit)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef) and \
                        _is_long_lived(node):
                    self._check_class(src, node, emit)
        return findings

    def _check_class(self, src: SourceFile, cls: ast.ClassDef,
                     emit) -> None:
        tracker = _Tracker()
        # collection attrs: self.x = {} / [] / set() / deque() ...
        for fn in [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            is_init = fn.name == "__init__"
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets \
                        if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        root = _root_attr(tgt)
                        if root is not None and root[0] == "self":
                            tracker.note_assign(
                                root[1], node.value, node.lineno,
                                is_init)
        _scan(ast.walk(cls), tracker, attr_root="self")
        tracker.findings(src.relpath, cls.name, emit)

    def _check_module(self, src: SourceFile, emit) -> None:
        tracker = _Tracker()
        for node in src.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                targets = [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    tracker.note_assign(tgt.id, node.value,
                                        node.lineno, is_init=True)
        # mutations anywhere in the module (function bodies included)
        _scan(ast.walk(src.tree), tracker, attr_root="")
        tracker.findings(src.relpath, "", emit)
