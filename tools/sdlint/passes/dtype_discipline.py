"""Pass: dtype-discipline — exact integer semantics in device kernels.

BLAKE3's compression function is uint32 wrap-around arithmetic; the
near-dup pyramid's index math is int32 by declaration. Inside device
code, two dtype hazards silently corrupt either the math or the trace
cache:

- `mixed-sign-arith`   — int32/uint32 operands in one arithmetic op:
  JAX promotes to int64 under x64 (different wrap-around!) and raises
  or weakly promotes elsewhere — either way the kernel's bit-exact
  contract is gone. Detection is a local dtype inference over
  assignments (`jnp.uint32(x)`, `.astype(jnp.int32)`, dtype'd creation
  calls, `jax.lax.axis_index`) extended one level interprocedurally:
  a call to a resolvable project function contributes that function's
  inferred return dtype.
- `implicit-dtype`     — `jnp.arange/zeros/ones/full` without a dtype
  (or `jnp.array/asarray` over bare numeric literals): the result
  dtype then depends on the x64 flag, so the same code traces int32
  programs in production and int64 ones wherever x64 is enabled — a
  retrace at best, different wrap semantics at worst.
- `builtin-dtype-cast` — `.astype(int)` / `dtype=float` with Python
  builtins: width follows the platform/x64 flag, not the kernel spec.

Scope: modules that import `jax.numpy` (device code), wherever they
live — the uint32 contract travels with the kernel, not the directory.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk

PASS = "dtype-discipline"

_INT_DTYPES = {"int8", "int16", "int32", "int64",
               "uint8", "uint16", "uint32", "uint64"}
_ALL_DTYPES = _INT_DTYPES | {"float32", "float64", "bfloat16", "float16",
                             "bool_", "bool"}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Mod,
              ast.LShift, ast.RShift, ast.BitOr, ast.BitAnd, ast.BitXor)
_CREATION = {"arange", "zeros", "ones", "full", "array", "asarray"}
# dtype position for creation calls that accept it positionally
_DTYPE_POS = {"zeros": 1, "ones": 1, "full": 2, "array": 1, "asarray": 1}


def _jnp_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to the jax.numpy module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.numpy":
                    out.add(alias.asname or "jax.numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for alias in node.names:
                    if alias.name == "numpy":
                        out.add(alias.asname or "numpy")
    return out


def _dtype_name(node: ast.AST) -> Optional[str]:
    """'uint32' for jnp.uint32 / np.uint32 / "uint32" expressions."""
    d = dotted(node)
    if d is not None:
        last = d.rsplit(".", 1)[-1]
        if last in _ALL_DTYPES:
            return "bool" if last == "bool_" else last
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _ALL_DTYPES:
        return node.value
    return None


def _call_dtype_kw(call: ast.Call, terminal: str) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return _dtype_name(kw.value)
    pos = _DTYPE_POS.get(terminal)
    if pos is not None and len(call.args) > pos:
        return _dtype_name(call.args[pos])
    return None


class _Inference:
    """Best-effort local dtype inference, with one-level
    interprocedural return-dtype propagation via the shared resolver."""

    def __init__(self, project: Project):
        self.idx = project.index
        self._ret_memo: Dict[str, Optional[str]] = {}

    def func_env(self, fn: FuncInfo) -> Dict[str, Optional[str]]:
        env: Dict[str, Optional[str]] = {}
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = self.of(node.value, env, fn)
        return env

    def return_dtype(self, fn: FuncInfo,
                     stack: frozenset = frozenset()) -> Optional[str]:
        key = f"{fn.src.relpath}::{fn.qual}"
        if key in self._ret_memo:
            return self._ret_memo[key]
        if key in stack:
            return None
        env = {}
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = self.of(
                    node.value, env, fn, stack | {key})
        rets = set()
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                rets.add(self.of(node.value, env, fn, stack | {key}))
        out = rets.pop() if len(rets) == 1 else None
        self._ret_memo[key] = out
        return out

    def of(self, node: ast.AST, env: Dict[str, Optional[str]],
           fn: FuncInfo, stack: frozenset = frozenset()) -> Optional[str]:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Subscript):
            return self.of(node.value, env, fn, stack)
        if isinstance(node, ast.UnaryOp):
            return self.of(node.operand, env, fn, stack)
        if isinstance(node, ast.BinOp):
            lt = self.of(node.left, env, fn, stack)
            rt = self.of(node.right, env, fn, stack)
            return lt if lt is not None else rt
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is None:
                # x.astype(D) and friends on non-dotted receivers
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args:
                    return _dtype_name(node.args[0])
                return None
            last = d.rsplit(".", 1)[-1]
            if last == "astype" and node.args:
                return _dtype_name(node.args[0])
            if last in _ALL_DTYPES:
                return "bool" if last == "bool_" else last
            if d == "jax.lax.axis_index":
                return "int32"
            if last in _CREATION:
                return _call_dtype_kw(node, last)
            callee = self.idx.resolve(fn, d)
            if callee is not None and not callee.is_async:
                return self.return_dtype(callee, stack)
        return None


def _signed_unsigned_pair(a: Optional[str], b: Optional[str]) -> bool:
    if a is None or b is None or a == b:
        return False
    if a not in _INT_DTYPES or b not in _INT_DTYPES:
        return False
    return a.startswith("uint") != b.startswith("uint")


class DtypeDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        inf = _Inference(project)
        for src in project.files:
            aliases = _jnp_aliases(src.tree)
            if not aliases:
                continue
            self._module_checks(src, aliases, findings)
            for fn in project.index.funcs:
                if fn.src is not src:
                    continue
                env = inf.func_env(fn)
                for node in own_body_walk(fn.node):
                    if isinstance(node, ast.BinOp) \
                            and isinstance(node.op, _ARITH_OPS):
                        lt = inf.of(node.left, env, fn)
                        rt = inf.of(node.right, env, fn)
                        if _signed_unsigned_pair(lt, rt):
                            expr = ast.unparse(node)[:60]
                            findings.append(Finding(
                                PASS, "mixed-sign-arith", src.relpath,
                                fn.qual, f"{lt}^{rt}:{expr}",
                                f"mixed {lt}/{rt} arithmetic `{expr}`: "
                                f"promotes to int64 under x64 (different "
                                f"wrap-around) — cast one side "
                                f"explicitly", node.lineno))
        return findings

    def _module_checks(self, src, aliases: Set[str],
                       findings: List[Finding]) -> None:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                # .astype(int) on computed receivers
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype":
                    self._builtin_cast(node, src, findings)
                continue
            parts = d.split(".")
            last = parts[-1]
            if last == "astype":
                self._builtin_cast(node, src, findings)
                continue
            base = ".".join(parts[:-1])
            if base not in aliases or last not in _CREATION:
                continue
            if self._dtype_is_builtin(node, last):
                findings.append(Finding(
                    PASS, "builtin-dtype-cast", src.relpath, "",
                    f"{d}:dtype",
                    f"`{d}` with a Python-builtin dtype: width follows "
                    f"the x64 flag, not the kernel spec — use an "
                    f"explicit jnp dtype", node.lineno))
                continue
            if _call_dtype_kw(node, last) is not None:
                continue
            if last in ("array", "asarray") \
                    and not self._bare_numeric(node):
                continue  # dtype-preserving conversion of an array var
            if last.endswith("_like"):
                continue
            findings.append(Finding(
                PASS, "implicit-dtype", src.relpath, "", d,
                f"`{d}` without an explicit dtype: the result is "
                f"int32 or int64 depending on the x64 flag — a silent "
                f"retrace (or wrap-semantics change) per flag state",
                node.lineno))

    @staticmethod
    def _dtype_is_builtin(call: ast.Call, terminal: str) -> bool:
        for kw in call.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Name) \
                    and kw.value.id in ("int", "float", "bool"):
                return True
        pos = _DTYPE_POS.get(terminal)
        if pos is not None and len(call.args) > pos \
                and isinstance(call.args[pos], ast.Name) \
                and call.args[pos].id in ("int", "float", "bool"):
            return True
        return False

    @staticmethod
    def _bare_numeric(call: ast.Call) -> bool:
        """array/asarray over literals (dtype chosen by VALUE)."""
        if not call.args:
            return False
        arg = call.args[0]
        if isinstance(arg, ast.Constant) \
                and isinstance(arg.value, (int, float)):
            return True
        if isinstance(arg, (ast.List, ast.Tuple)) and arg.elts and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, (int, float)) for e in arg.elts):
            return True
        if isinstance(arg, ast.Call) and dotted(arg.func) == "len":
            return True
        return False

    def _builtin_cast(self, node: ast.Call, src,
                      findings: List[Finding]) -> None:
        if node.args and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in ("int", "float", "bool"):
            expr = ast.unparse(node)[:60]
            findings.append(Finding(
                PASS, "builtin-dtype-cast", src.relpath, "",
                f"astype:{node.args[0].id}",
                f"`{expr}`: .astype({node.args[0].id}) width follows "
                f"the x64 flag — use an explicit jnp dtype",
                node.lineno))
