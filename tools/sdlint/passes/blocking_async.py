"""Pass: blocking-async — blocking work reachable from `async def`.

An event-loop callback that blocks (sqlite, file IO, subprocess,
time.sleep, native batch encoders, future/thread waits) starves every
other task on the node: the watcher debounce, p2p acks, job progress
events. The discipline is `await asyncio.to_thread(...)` (or an
executor) around anything that touches a syscall or the GIL for long.

Detection, two layers:

1. direct — a blocking root call in an `async def` body that is not
   awaited (awaited calls are async by construction), not passed into
   a thread wrapper, and not inside a nested function;
2. interprocedural — the async function calls a resolvable SYNC
   project function whose transitive closure contains a blocking root
   (reported with the discovered call chain).

The resolver is the shared three-tier one (core.ProjectIndex.resolve);
dynamic dispatch it cannot see is covered at runtime by the
sanitizer's loop-stall detector — the two tools are designed as a
pair.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import (
    CallSite,
    Finding,
    FuncInfo,
    Project,
    dotted,
    own_body_walk,
)

PASS = "blocking-async"

# Dotted-name roots (exact or prefix) that always mean a blocking call.
_EXACT = {
    "time.sleep", "os.scandir", "os.walk", "os.listdir", "os.replace",
    "os.makedirs", "os.rename", "os.stat", "os.read", "os.write",
    "open", "os.fsync",
}
_PREFIXES = ("subprocess.", "shutil.")

# Method names that hit SQLite when the receiver looks like a
# Database / connection / cursor (this codebase's naming idiom).
_DB_METHODS = {
    "query", "query_one", "execute", "executemany", "executescript",
    "run", "run_many", "run_tx",
    "commit", "rollback", "insert", "insert_many", "update", "upsert",
    "delete", "tx", "checkpoint", "checkpoint_passive",
    "ensure_lazy_indexes",
}
_DB_RECEIVERS = {"db", "conn", "connection", "cur", "cursor", "c"}

# SyncManager entry points that run SQL under the hood.
_SYNC_METHODS = {
    "get_ops", "receive_crdt_operations", "receive_blob_pages",
    "iter_clone_stream", "bulk_shared_ops", "drain_quarantined_ops",
    "write_ops",
}

# ctypes-backed native batch calls (CPU-bound for the whole page).
_NATIVE = {"sd_encode_ops", "sd_decode_ops", "compile_library"}


def classify_blocking(call: ast.Call) -> Optional[str]:
    """Stable ident of the blocking root this call is, else None."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    last = parts[-1]
    recv = parts[:-1]
    if d in _EXACT or d.startswith(_PREFIXES):
        return d
    if last in _NATIVE:
        return d
    if last in _DB_METHODS and any(
            p in _DB_RECEIVERS for p in recv):
        return d
    if last in _SYNC_METHODS and ("sync" in recv or not recv):
        return d
    # Cross-thread waits: a parameterless .result()/.join() is a
    # future/thread wait (str.join and os.path.join always take args).
    # Receivers named *task* are asyncio tasks — their .result() after
    # an `await asyncio.wait(...)` is a non-blocking retrieval.
    if last in ("result", "join") and not call.args and not call.keywords \
            and not any("task" in p for p in recv):
        return d
    # Passing a live Database handle into a helper
    # (`report.update(library.db)`) — the helper writes with it.
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        ad = dotted(arg)
        if ad is not None and (ad == "db" or ad.split(".")[-1] == "db"):
            return f"{d}(*.db)"
    return None


def _awaited_call_ids(fn_node: ast.AST) -> set:
    out = set()
    for node in own_body_walk(fn_node):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


class BlockingAsyncPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        idx = project.index
        # -- phase 1: does each SYNC function transitively block? ----
        # memo: key → (root ident, chain of qualnames) or None
        memo: Dict[str, Optional[Tuple[str, List[str]]]] = {}

        def blocking_of(fn: FuncInfo, stack: frozenset
                        ) -> Optional[Tuple[str, List[str]]]:
            key = f"{fn.src.relpath}::{fn.qual}"
            if key in memo:
                return memo[key]
            if key in stack:
                return None  # recursion guard; cycle adds nothing
            best: Optional[Tuple[str, List[str]]] = None
            for site in fn.calls:
                if site.wrapped:
                    continue
                root = classify_blocking(site.node)
                if root is not None:
                    best = (root, [fn.qual])
                    break
            if best is None:
                for site in fn.calls:
                    if site.wrapped:
                        continue
                    callee = idx.resolve(fn, site.name)
                    if callee is None or callee.is_async:
                        continue
                    sub = blocking_of(callee, stack | {key})
                    if sub is not None:
                        best = (sub[0], [fn.qual] + sub[1])
                        break
            memo[key] = best
            return best

        findings: List[Finding] = []
        for fn in idx.funcs:
            if not fn.is_async:
                continue
            awaited = _awaited_call_ids(fn.node)
            seen_idents = set()
            for site in fn.calls:
                if site.wrapped or id(site.node) in awaited:
                    continue
                root = classify_blocking(site.node)
                if root is not None:
                    ident = f"direct:{root}"
                    if ident in seen_idents:
                        continue
                    seen_idents.add(ident)
                    findings.append(Finding(
                        PASS, "direct", fn.src.relpath, fn.qual, ident,
                        f"blocking call `{site.name}` on the event loop "
                        f"(wrap in asyncio.to_thread)",
                        site.node.lineno))
                    continue
                callee = idx.resolve(fn, site.name)
                if callee is None or callee.is_async:
                    continue
                sub = blocking_of(callee, frozenset())
                if sub is not None:
                    chain = " -> ".join(sub[1])
                    ident = f"via:{site.name}:{sub[0]}"
                    if ident in seen_idents:
                        continue
                    seen_idents.add(ident)
                    findings.append(Finding(
                        PASS, "reach", fn.src.relpath, fn.qual, ident,
                        f"call `{site.name}` reaches blocking "
                        f"`{sub[0]}` (via {chain}) on the event loop "
                        f"(wrap in asyncio.to_thread)",
                        site.node.lineno))
        return findings
