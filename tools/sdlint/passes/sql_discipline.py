"""Pass: sql-discipline — every SQL statement executes by contract.

The store's machine-checked seam (store/statements.py + Database.run)
only holds if no SQL can reach an execute method outside it. Codes:

- `sql-literal`       — a DML string literal (SELECT/INSERT/UPDATE/
  DELETE/REPLACE/WITH) passed to an execute method (`conn.execute`,
  `executemany`, `db.query`, `query_one`). Literals migrate to a
  `declare_stmt` + `db.run(name)`; ad-hoc diagnostic reads belong to
  tests (outside the lint scope), not product code.
- `sql-dynamic`       — dynamically-BUILT SQL (f-string, `%`,
  `.format`, `+`-concatenation) reaching an execute method whose
  rendered skeleton matches NO declared shape. Matching a shape is
  the sanctioned dynamic form (registry-derived identifiers, checked
  again at runtime by the auditor).
- `sql-opaque`        — an execute method fed an expression the pass
  cannot see through (a name not assigned SQL in the same function, a
  call other than `statements.get(...).sql` / `statements.sql(...)`).
  Opaque SQL defeats the static half of the contract; route it
  through the registry or waive with a reason.
- `run-unknown`       — `run`/`run_many`/`run_tx` with a literal name
  absent from the registry (typo guard, cross-AST vs statements.py).
- `run-dynamic-name`  — `run`/`run_many`/`run_tx` with a non-literal
  name: the registry linkage must be statically visible (same rule as
  the timeout/channel registries).
- `write-no-conn`     — `run`/`run_many` of a write-verb statement
  without `conn=`: writes execute on the open tx() connection
  (`run_tx` is the single-statement sugar). Interprocedural half: a
  function whose `conn` parameter feeds write statements must only be
  reached from tx scopes — checked via the same with-tx lexing
  lock-discipline uses, one caller hop deep.
- `read-via-write-path` — `.execute`/`.executemany` invoked on a
  Database receiver (`*.db`): the old write-wrapping `Database
  .execute` is gone precisely because it routed reads through the
  write lock; nothing may grow it back.
- `sql-central`       — `declare_stmt`/`declare_shape` outside
  spacedrive_tpu/store/statements.py (fixtures waive inline).

`store/db.py` is the whitelisted engine room: the typed helpers and
schema bootstrap build SQL by design, and every statement they emit is
still matched at runtime by the audited connection.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk
from . import _sql

PASS = "sql-discipline"

_EXEC_LASTS = {"execute", "executemany", "query", "query_one"}
_RUN_LASTS = {"run", "run_many", "run_tx"}
# `.run()` is ubiquitous (subprocess, CLIs, jobs) — only Database
# receivers participate, same receiver idiom as blocking-async.
_DB_RECEIVERS = {"db"}
_ENGINE_ROOM = ("spacedrive_tpu/store/db.py",
                "spacedrive_tpu/store/sqlaudit.py")
_CENTRAL = _sql.STATEMENTS_PATH


def _local_sql_assignments(fn: FuncInfo) -> Dict[str, ast.AST]:
    """name → value for simple assignments whose value is (or builds)
    SQL text, so `sql = f"..."; conn.execute(sql)` resolves."""
    out: Dict[str, ast.AST] = {}
    for node in own_body_walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name):
            # `where += ...` — dynamic build-up; keep the target known
            out.setdefault(node.target.id, node.value)
    return out


def _is_registry_sql_expr(node: ast.AST) -> bool:
    """`statements.get("x").sql` / `statements.sql("x")` — SQL pulled
    FROM the registry is contract-bound by construction."""
    if isinstance(node, ast.Attribute) and node.attr == "sql":
        inner = node.value
        if isinstance(inner, ast.Call):
            d = dotted(inner.func)
            if d is not None and d.split(".")[-1] == "get":
                return True
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d is not None and d.split(".")[-1] == "sql":
            return True
    return False


class SqlDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        decls = _sql.project_decls(project)
        shapes = _sql.ShapeIndex(decls)
        findings: List[Finding] = []
        # functions that execute write statements on a conn PARAMETER:
        # qual → statement name (for the interprocedural check)
        conn_writers: Dict[str, str] = {}
        for fn in project.index.funcs:
            self._scan_fn(fn, decls, shapes, findings, conn_writers)
        self._check_conn_writers(project, conn_writers, findings)
        for src in project.files:
            if src.relpath == _CENTRAL:
                continue
            for d in _sql.decls_in_tree(src.tree, src.relpath):
                findings.append(Finding(
                    PASS, "sql-central", src.relpath, "", d.name,
                    f"statement {d.name!r} declared outside the "
                    f"central registry ({_CENTRAL})", d.lineno))
        return findings

    # -- per-function -------------------------------------------------------

    def _scan_fn(self, fn: FuncInfo, decls, shapes, findings,
                 conn_writers) -> None:
        rel = fn.src.relpath
        if rel.startswith(_ENGINE_ROOM) or rel == _CENTRAL:
            return
        assigns = None
        in_tx = _fn_tx_lines(fn)
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            last = parts[-1]
            recv = parts[:-1]
            if last in _RUN_LASTS and recv \
                    and recv[-1] in _DB_RECEIVERS:
                self._check_run(fn, node, last, decls, findings,
                                conn_writers, in_tx)
                continue
            if last not in _EXEC_LASTS or not node.args:
                continue
            if recv and recv[-1] == "db" and last in (
                    "execute", "executemany"):
                findings.append(Finding(
                    PASS, "read-via-write-path", rel, fn.qual, d,
                    "Database.execute is gone — it wrapped reads in a "
                    "write transaction; use run()/run_tx()/query()",
                    node.lineno))
                continue
            arg = node.args[0]
            lit = _sql.literal_sql(arg)
            if lit is not None:
                findings.append(Finding(
                    PASS, "sql-literal", rel, fn.qual,
                    _sql.normalize_sql(lit)[:60],
                    "raw SQL literal at an execute method — declare "
                    "it in store/statements.py and call db.run()",
                    node.lineno))
                continue
            dyn = _sql.dynamic_sql_expr(arg)
            if dyn is None and isinstance(arg, ast.Name):
                if assigns is None:
                    assigns = _local_sql_assignments(fn)
                src_expr = assigns.get(arg.id)
                if src_expr is not None:
                    lit = _sql.literal_sql(src_expr)
                    if lit is not None:
                        findings.append(Finding(
                            PASS, "sql-literal", rel, fn.qual,
                            _sql.normalize_sql(lit)[:60],
                            "raw SQL literal (via local variable) at "
                            "an execute method — declare it in "
                            "store/statements.py", node.lineno))
                        continue
                    dyn = _sql.dynamic_sql_expr(src_expr)
            if dyn is not None:
                if shapes.match(dyn) is None:
                    findings.append(Finding(
                        PASS, "sql-dynamic", rel, fn.qual,
                        _sql.normalize_sql(dyn)[:60],
                        "dynamically-built SQL matches no declared "
                        "shape (store/statements.py declare_shape)",
                        node.lineno))
                continue
            if isinstance(arg, ast.Constant):
                continue  # non-SQL constant (not our business)
            if _is_registry_sql_expr(arg):
                continue
            if isinstance(arg, (ast.Name, ast.Attribute, ast.Call,
                                ast.Subscript)):
                findings.append(Finding(
                    PASS, "sql-opaque", rel, fn.qual, d,
                    "execute method fed SQL the pass cannot see "
                    "through — route it through the statement "
                    "registry", node.lineno))

    def _check_run(self, fn, node, last, decls, findings,
                   conn_writers, in_tx) -> None:
        rel = fn.src.relpath
        if not node.args:
            return
        name_node = node.args[0]
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            findings.append(Finding(
                PASS, "run-dynamic-name", rel, fn.qual,
                dotted(node.func) or last,
                f"{last}() with a non-literal statement name — the "
                "registry linkage must be statically visible",
                node.lineno))
            return
        name = name_node.value
        decl = decls.get(name)
        if decl is None:
            findings.append(Finding(
                PASS, "run-unknown", rel, fn.qual, name,
                f"statement {name!r} is not declared in "
                "store/statements.py", node.lineno))
            return
        if last == "run_tx":
            return  # opens its own tx; tx-shape watches loops
        if decl.verb == "write":
            conn_kw = next((kw for kw in node.keywords
                            if kw.arg == "conn"), None)
            if conn_kw is None:
                findings.append(Finding(
                    PASS, "write-no-conn", rel, fn.qual, name,
                    f"write statement {name!r} without conn= — writes "
                    "execute on the open tx() connection (or use "
                    "run_tx)", node.lineno))
            elif isinstance(conn_kw.value, ast.Name) \
                    and node.lineno not in in_tx:
                # conn came from a parameter (not a lexical tx): the
                # caller side must prove tx scope. A with-binding of
                # the same name (incl. the conditional
                # `with (db.tx() if own_tx else nullcontext(conn))`
                # own-tx idiom) makes the function self-sufficient.
                arg_names = {a.arg for a in fn.node.args.args}
                if conn_kw.value.id in arg_names and \
                        conn_kw.value.id not in _with_bound_names(fn):
                    conn_writers.setdefault(fn.qual, name)

    # -- interprocedural: conn-parameter writers ----------------------------

    def _check_conn_writers(self, project, conn_writers, findings):
        """One hop up: every resolvable caller of a conn-parameter
        writer must sit in a with-tx scope, receive conn itself, or
        pass a conn kwarg/arg visibly. (Deeper chains are the runtime
        auditor's job — autocommit writes raise.)"""
        if not conn_writers:
            return
        for fn in project.index.funcs:
            in_tx = _fn_tx_lines(fn)
            has_conn_param = "conn" in {a.arg for a in fn.node.args.args}
            for site in fn.calls:
                callee = project.index.resolve(fn, site.name)
                if callee is None or callee.qual not in conn_writers:
                    continue
                if has_conn_param or site.node.lineno in in_tx:
                    continue
                passes_conn = any(kw.arg == "conn"
                                  for kw in site.node.keywords) or \
                    any(isinstance(a, ast.Name) and a.id == "conn"
                        for a in site.node.args)
                if passes_conn:
                    continue
                findings.append(Finding(
                    PASS, "write-outside-tx", fn.src.relpath, fn.qual,
                    f"{site.name}->{conn_writers[callee.qual]}",
                    f"calls {site.name}() which writes "
                    f"{conn_writers[callee.qual]!r} on its conn "
                    "parameter, but no tx() scope or conn is visible "
                    "here", site.node.lineno))


def _with_bound_names(fn: FuncInfo) -> set:
    """Names bound by `with ... as <name>` anywhere in the function."""
    out = set()
    for node in own_body_walk(fn.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    out.add(item.optional_vars.id)
    return out


def _fn_tx_lines(fn: FuncInfo) -> set:
    """Line numbers lexically inside a `with ...tx():` /
    `with ...write_ops(...)` body in this function."""
    out = set()
    for node in own_body_walk(fn.node):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call):
                d = dotted(ctx.func)
                if d is not None and d.split(".")[-1] in (
                        "tx", "write_ops"):
                    for sub in ast.walk(node):
                        if hasattr(sub, "lineno"):
                            out.add(sub.lineno)
    return out
