"""Pass: host-transfer — no stray D2H fetches in device pipelines.

A `np.asarray(...)`, `.item()`, implicit `bool(arr)`, or
`block_until_ready()` on a live device value forces a synchronous
device→host round trip — through the tunneled bench chip that is
multiple milliseconds of RPC per call, and in the identify loop a
single stray fetch serializes the whole depth-N overlap pipeline.
The discipline: every transfer of jit results happens at a DECLARED
point — a `with jit_registry.io("<contract>"):` scope whose contract
(ops/jit_registry.py) is declared `host_transfer=True` — or runs
off-loop via to_thread, or is baselined with a reason.

Detection is lexical over "device-consumer" functions — those whose
body calls a registered jit entry point (by bound callable name) or
`jax.device_put`:

- `undeclared-transfer` — np.asarray / np.array / jax.device_get /
  `.item()` / `.block_until_ready()` outside any io(...) scope.
  np.asarray *inside the argument list* of a jit-entry call is input
  prep (H2D), not a result fetch, and is exempt;
- `implicit-host-cast` / `implicit-host-bool` — `int()/float()/bool()`
  or a bare `if`/`while` test over a variable assigned from a jit
  entry call: the hidden `__bool__`/`__float__` is a full D2H sync;
- `undeclared-io` — an `io(name)` scope whose name is not a declared
  host_transfer contract (the registry must stay authoritative).

Dataflow through variables ACROSS functions is out of scope by design
(same note as blocking-async): the runtime transfer guard armed by the
sanitizer inside `device_scope()` regions covers that half.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, FuncInfo, Project, dotted, own_body_walk
from .jit_stability import _tracked_name, declared_contracts

PASS = "host-transfer"

_THREAD_WRAPPERS = {"to_thread", "run_in_executor", "submit"}


def _jit_entry_names(project: Project, contracts: Dict[str, dict]
                     ) -> Set[str]:
    """Callable names that dispatch registered device work: contract
    site terminals plus every tracked/jit-decorated def in the tree
    (fixtures carry their own local jits)."""
    names: Set[str] = set()
    for c in contracts.values():
        qual = c["site"].split("::", 1)[-1]
        if qual:
            names.add(qual.rsplit(".", 1)[-1])
    for fn in project.index.funcs:
        node = fn.node
        decos = getattr(node, "decorator_list", [])
        for deco in decos:
            if dotted(deco) in ("jax.jit", "jit") \
                    or _tracked_name(deco) is not None:
                names.add(fn.name)
            if isinstance(deco, ast.Call) and dotted(deco.func) \
                    and dotted(deco.func).rsplit(".", 1)[-1] == "partial":
                if deco.args and dotted(deco.args[0]) in ("jax.jit",
                                                          "jit"):
                    names.add(fn.name)
    return names


def _io_scope_name(with_node: ast.With) -> Optional[str]:
    for item in with_node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call) and dotted(ce.func) is not None \
                and dotted(ce.func).rsplit(".", 1)[-1] == "io" \
                and ce.args and isinstance(ce.args[0], ast.Constant) \
                and isinstance(ce.args[0].value, str):
            return ce.args[0].value
    return None


class HostTransferPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        contracts = declared_contracts(project.root)
        jit_names = _jit_entry_names(project, contracts)
        findings: List[Finding] = []
        for fn in project.index.funcs:
            if self._is_consumer(fn, jit_names):
                self._check_fn(fn, jit_names, contracts, findings)
        return findings

    @staticmethod
    def _is_consumer(fn: FuncInfo, jit_names: Set[str]) -> bool:
        for site in fn.calls:
            last = site.name.rsplit(".", 1)[-1]
            if last in jit_names or site.name in ("jax.device_put",
                                                  "device_put"):
                return True
        return False

    def _check_fn(self, fn: FuncInfo, jit_names: Set[str],
                  contracts: Dict[str, dict],
                  findings: List[Finding]) -> None:
        src = fn.src
        wrapped_ids = {id(s.node) for s in fn.calls if s.wrapped}
        # argument subtrees of jit-entry calls: input prep, not fetch
        prep_ids: Set[int] = set()
        jit_vars: Set[str] = set()
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Call) and dotted(node.func):
                last = dotted(node.func).rsplit(".", 1)[-1]
                if last in jit_names:
                    for arg in list(node.args) + [kw.value
                                                  for kw in node.keywords]:
                        for sub in ast.walk(arg):
                            prep_ids.add(id(sub))
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and dotted(node.value.func) is not None \
                    and dotted(node.value.func).rsplit(".", 1)[-1] \
                    in jit_names:
                jit_vars.add(node.targets[0].id)

        def emit(code: str, ident: str, msg: str, lineno: int) -> None:
            findings.append(Finding(
                PASS, code, src.relpath, fn.qual, ident, msg, lineno))

        def walk(node: ast.AST, declared: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return
            if isinstance(node, ast.With):
                name = _io_scope_name(node)
                if name is not None:
                    c = contracts.get(name)
                    if c is None or not c.get("host_transfer"):
                        emit("undeclared-io", name,
                             f"io({name!r}) is not a declared "
                             f"host_transfer contract in the jit "
                             f"registry", node.lineno)
                    for child in node.body:
                        walk(child, True)
                    return
            if isinstance(node, (ast.If, ast.While)) \
                    and isinstance(node.test, ast.Name) \
                    and node.test.id in jit_vars:
                emit("implicit-host-bool", node.test.id,
                     f"bare truth test over jit result "
                     f"`{node.test.id}` forces a blocking D2H sync "
                     f"(fetch explicitly inside a declared io scope)",
                     node.lineno)
            if isinstance(node, ast.Call):
                self._check_call(node, declared, wrapped_ids, prep_ids,
                                 jit_vars, emit)
            for child in ast.iter_child_nodes(node):
                walk(child, declared)

        for stmt in ast.iter_child_nodes(fn.node):
            walk(stmt, False)

    @staticmethod
    def _check_call(node: ast.Call, declared: bool, wrapped_ids: Set[int],
                    prep_ids: Set[int], jit_vars: Set[str], emit) -> None:
        if declared or id(node) in wrapped_ids:
            return
        d = dotted(node.func)
        idiom = None
        if d is not None:
            parts = d.split(".")
            last = parts[-1]
            base = ".".join(parts[:-1])
            if last in ("asarray", "array") and base in ("np", "numpy"):
                idiom = "np." + last
            elif d in ("jax.device_get", "device_get"):
                idiom = "device_get"
            elif last in ("int", "float", "bool") and len(parts) == 1 \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in jit_vars:
                emit("implicit-host-cast", f"{last}:{node.args[0].id}",
                     f"{last}() over jit result `{node.args[0].id}` is "
                     f"an implicit D2H sync (fetch inside a declared io "
                     f"scope)", node.lineno)
                return
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                idiom = ".item()"
            elif node.func.attr == "block_until_ready":
                idiom = "block_until_ready"
        if idiom is None or id(node) in prep_ids:
            return
        emit("undeclared-transfer", f"{idiom}:{d or '?'}",
             f"`{idiom}` in a device-consumer function outside any "
             f"declared io(...) scope — wrap the fetch in "
             f"jit_registry.io(<contract>) (host_transfer=True), "
             f"offload via to_thread, or baseline with a reason",
             node.lineno)
