"""Pass: guard-consistency — one attribute, one guard (RacerD-style).

An attribute written under `with self._x_lock:` at one site and bare
(or under a DIFFERENT lock) at another is the classic inconsistent-
lock-protection smell: the guarded site documents that concurrent
access exists, so the bare site is a lost-update/torn-read candidate —
exactly the evidence-based heuristic Facebook's RacerD made scale
(O'Hearn, POPL'18): no alias analysis, just "this field is sometimes
protected, and here it isn't".

Scope: per-class `self.<attr>` mutation sites (rebinds, augmented
updates, container mutations) outside `__init__`/`__post_init__`.
Classes registered in the threadctx.py ownership registry are EXEMPT —
their attrs are held to the stronger declared contract by the
shared-mutation pass; this pass exists to catch the classes nobody
declared yet.

Code:

- ``mixed-guard`` — an attr with at least one guarded mutation site
  and at least one site bare or under a different lock. The ident is
  `Class.attr`; the message names both locksets and both sites.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..core import Finding, Project
from ._threads import (
    MutationSite,
    class_hierarchy,
    collect_mutations,
    declared_owners,
    effective_owner,
    owners_by_class,
)

PASS = "guard-consistency"


class GuardConsistencyPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_owners(project.root, project)
        by_class = owners_by_class(declared)
        hierarchy = class_hierarchy(project)
        registered = {
            name for name in hierarchy
            if effective_owner(name, by_class, hierarchy) is not None
        } | set(by_class)
        # Same `known` set as shared-mutation so the memoized
        # whole-tree sweep is genuinely shared (one walk per lint);
        # the extra annotation-resolved sites it adds are filtered
        # right below by the self_recv test.
        sites = collect_mutations(project, set(by_class))
        findings: List[Finding] = []
        seen: Set[str] = set()

        grouped: Dict[Tuple[str, str, str], List[MutationSite]] = {}
        for s in sites:
            if not s.self_recv or s.in_init:
                continue
            if s.cls_name in registered:
                continue  # shared-mutation enforces the real contract
            grouped.setdefault(
                (s.fn.src.relpath, s.cls_name, s.attr), []).append(s)

        for (relpath, cls_name, attr), group in sorted(grouped.items()):
            guarded = [s for s in group if s.locks]
            bare = [s for s in group if not s.locks]
            if not guarded:
                continue  # never protected: no claimed invariant
            common = frozenset.intersection(
                *[frozenset(s.locks) for s in group])
            if common:
                continue  # one lock covers every site (extras are fine)
            g0 = min(guarded, key=lambda s: s.lineno)
            if bare:
                other = min(bare, key=lambda s: s.lineno)
                shape = (f"bare at {other.fn.qual}:{other.lineno}")
            else:
                # Two different locks — still inconsistent. The cited
                # counter-site must be one whose lockset actually
                # DIFFERS from g0's, or the diagnostic points at
                # itself.
                other = min((s for s in guarded
                             if s.locks != g0.locks),
                            key=lambda s: s.lineno)
                shape = (f"under {sorted(other.locks)} at "
                         f"{other.fn.qual}:{other.lineno}")
            f = Finding(
                PASS, "mixed-guard", relpath, g0.fn.qual,
                f"{cls_name}.{attr}",
                f"`{cls_name}.{attr}` is mutated under "
                f"{sorted(g0.locks)} here but {shape} — inconsistent "
                "guard means the lock protects nothing; hold the same "
                "lock everywhere or declare the class in "
                "threadctx.py",
                g0.lineno)
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)
        return findings
