"""Pass: crdt-parity — shared-model writes must emit sync ops.

The sync layer's core contract (PR 1/PR 2): every local write to a
SHARED or RELATION model table logs a CRDT op **in the same
transaction** — that is what makes a fresh peer's replica converge
byte-identically. A domain write that skips the op log never syncs,
silently, forever.

Shared/relation table names come from the model registry
(`spacedrive_tpu/store/models.py`), parsed as AST — no package import,
so the linter runs anywhere. A write site is:

- `conn.execute/executemany("INSERT INTO <t> ...")` (or UPDATE /
  DELETE FROM) with a string-literal SQL mentioning such a table, or
- a Database helper (`db.insert("t", ...)`, insert_many / update /
  upsert / delete) whose first argument is such a table literal.

A write complies when its enclosing function also emits ops: a
`with ...write_ops(...)` context, or a call to `bulk_shared_ops` /
`_insert_op_rows`. Function-level granularity keeps false positives
near zero at this codebase's idiom (the op list is always built next
to the write).

Exempt by design: the sync engine itself (`sync/`), which writes
shared tables when APPLYING remote ops; `store/` (schema/DDL);
`backups.py` (byte-level replay of an already-op-logged database).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set

from ..core import Finding, Project, dotted, own_body_walk
from . import _sql

PASS = "crdt-parity"

_HELPERS = {"insert", "insert_many", "update", "upsert", "delete"}
_RUN_LASTS = {"run", "run_many", "run_tx"}
_EMITTERS = {"bulk_shared_ops", "_insert_op_rows", "write_ops"}
_EXEMPT_PREFIXES = ("spacedrive_tpu/sync/", "spacedrive_tpu/store/")
_EXEMPT_FILES = {"spacedrive_tpu/backups.py"}


def synced_tables(root: str) -> Set[str]:
    """SHARED + RELATION table names from store/models.py, by AST:
    `register(Model("name", ..., sync=SyncMode.SHARED, ...))`."""
    path = os.path.join(root, "spacedrive_tpu", "store", "models.py")
    out: Set[str] = set()
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "register"):
            continue
        for arg in node.args:
            if not (isinstance(arg, ast.Call)
                    and dotted(arg.func) == "Model"):
                continue
            name = None
            if arg.args and isinstance(arg.args[0], ast.Constant) \
                    and isinstance(arg.args[0].value, str):
                name = arg.args[0].value
            for kw in arg.keywords:
                if kw.arg == "sync":
                    mode = dotted(kw.value) or ""
                    if mode.endswith((".SHARED", ".RELATION")) and name:
                        out.add(name)
    return out


def _sql_write_tables(sql: str, tables: Set[str]) -> List[str]:
    hits = []
    for t in tables:
        if re.search(
            rf"\b(INSERT\s+(?:OR\s+\w+\s+)?INTO|UPDATE|DELETE\s+FROM)\s+"
            rf"{re.escape(t)}\b", sql, re.IGNORECASE,
        ):
            hits.append(t)
    return sorted(hits)


def _string_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    # implicit concatenation parses to a single Constant; f-strings and
    # joins stay dynamic → not analyzable, skip
    return None


class CrdtParityPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        tables = synced_tables(project.root)
        if not tables:
            return []
        # registry view: run("name") write statements resolve their
        # target tables through store/statements.py (round 16 — the
        # SQL text moved out of the call sites).
        decls = _sql.project_decls(project)
        findings: List[Finding] = []
        for fn in project.index.funcs:
            rel = fn.src.relpath
            if rel.startswith(_EXEMPT_PREFIXES) or rel in _EXEMPT_FILES:
                continue
            emits = self._emits_ops(fn.node)
            seen: Set[str] = set()
            for node in own_body_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._write_target(node, tables, decls)
                if hit is None or emits:
                    continue
                if hit in seen:
                    continue
                seen.add(hit)
                findings.append(Finding(
                    PASS, "silent-write", rel, fn.qual, hit,
                    f"writes synced table {hit!r} without emitting a "
                    f"CRDT op in scope (use sync.write_ops / "
                    f"bulk_shared_ops in the same tx)", node.lineno))
        return findings

    @staticmethod
    def _emits_ops(fn_node: ast.AST) -> bool:
        for node in own_body_walk(fn_node):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None and d.split(".")[-1] in _EMITTERS:
                    return True
        return False

    @staticmethod
    def _write_target(call: ast.Call, tables: Set[str],
                      decls=None) -> Optional[str]:
        d = dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        last = parts[-1]
        recv = parts[:-1]
        if last in ("execute", "executemany") and call.args:
            sql = _string_const(call.args[0])
            if sql:
                hits = _sql_write_tables(sql, tables)
                if hits:
                    return hits[0]
        if last in _RUN_LASTS and decls and call.args:
            name = _string_const(call.args[0])
            if name:
                decl = decls.get(name)
                if decl is not None and decl.verb == "write":
                    hits = sorted(set(decl.tables) & tables)
                    if hits:
                        return hits[0]
        if last in _HELPERS and recv and recv[-1] in ("db", "conn") \
                and call.args:
            t = _string_const(call.args[0])
            if t in tables:
                return t
        return None
