"""Pass: crash-atomicity — one function, one commit point.

`persist.atomic_write` makes each ARTIFACT land whole-or-not-at-all,
but a function that commits TWO artifacts (or an artifact plus a DB
transaction) has a crash window between them where the pair disagrees
— the config that points at a database image the kill arrived before,
the index header that says "acked" over a bundle file that still says
open. The static rule cannot prove which orderings are safe, so it
demands the author SAY so: every multi-commit function carries an
inline waiver whose comment states the commit order and why a crash
between the points recovers (idempotent re-run, ordered
db-before-config, second write advisory...). The crash grid
(tools/crash_grid.py) then kills the process AT each declared edge
and holds the recovery story to account.

Codes:

- ``multi-commit``: a function whose own body reaches two or more
  distinct durable commit points — persist writes with different
  artifact names, or a persist write plus a DB write
  (`write_tx` / `db.insert` / `persist.db_write`) — with no declared
  ordering (the waiver comment IS the declaration).
- ``rmw-unguarded``: read-modify-write of a declared artifact (the
  function both reads a file and persist-writes an artifact) outside
  any lock context or O_EXCL guard: two concurrent writers interleave
  to a torn logical state even though each WRITE is atomic.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "crash-atomicity"

CENTRAL = "spacedrive_tpu/persist.py"
PRODUCT_PREFIX = "spacedrive_tpu/"
SCOPE_MARKER = "# sdlint-scope: persist"

# persist entry points that COMMIT (scratch/recover/edges_for do not).
_PERSIST_COMMITS = {"atomic_write", "seal", "wal_writer"}
_DB_COMMITS = {"write_tx", "db_write"}


def _persist_commit_name(call: ast.Call, d: str) -> str:
    """The literal artifact name iff this call is a persist commit."""
    last = d.rsplit(".", 1)[-1]
    if last not in _PERSIST_COMMITS or "persist." not in d:
        return ""
    arg = call.args[0] if call.args else None
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return ""


def _is_db_commit(d: str) -> bool:
    last = d.rsplit(".", 1)[-1]
    if last in _DB_COMMITS:
        return True
    # `<anything>.db.insert(...)` — a row landed durably (SQLite WAL
    # owns that commit point).
    parts = d.split(".")
    return last == "insert" and len(parts) >= 2 and parts[-2] == "db"


def _has_lock_guard(fn) -> bool:
    """Any `with <...lock...>:` / `async with <...lock...>:` block or
    an O_EXCL open in the function's own body."""
    for node in own_body_walk(fn.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                d = dotted(item.context_expr) or ""
                if isinstance(item.context_expr, ast.Call):
                    d = dotted(item.context_expr.func) or ""
                if "lock" in d.lower() or "mutex" in d.lower():
                    return True
        if isinstance(node, ast.Attribute) and node.attr == "O_EXCL":
            return True
    return False


def _reads_files(fn) -> bool:
    """The function opens something for read (or json.load's a file
    object) in its own body — the READ half of a read-modify-write."""
    for site in fn.calls:
        d = site.name
        last = d.rsplit(".", 1)[-1]
        if d == "open":
            call = site.node
            mode = None
            if len(call.args) > 1:
                mode = call.args[1]
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if mode is None:
                return True
            if isinstance(mode, ast.Constant) and \
                    isinstance(mode.value, str) and \
                    "r" in mode.value and "+" not in mode.value:
                return True
        if d == "json.load":
            return True
    return False


class CrashAtomicityPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            rel = fn.src.relpath
            head = "\n".join(fn.src.lines[:5])
            if rel == CENTRAL or not (rel.startswith(PRODUCT_PREFIX)
                                      or SCOPE_MARKER in head):
                continue
            commits: List[tuple] = []   # (ident, lineno)
            persist_names: List[str] = []
            for site in fn.calls:
                name = _persist_commit_name(site.node, site.name)
                if name:
                    commits.append((name, site.node.lineno))
                    persist_names.append(name)
                elif _is_db_commit(site.name):
                    commits.append(("db", site.node.lineno))
            idents = {c[0] for c in commits}
            if len(idents) >= 2:
                first = min(commits, key=lambda c: c[1])
                emit(Finding(
                    PASS, "multi-commit", rel, fn.qual,
                    "+".join(sorted(idents)),
                    "multiple durable commit points "
                    f"({', '.join(sorted(idents))}) with no declared "
                    "ordering: a crash between them leaves the pair "
                    "disagreeing — declare the order and the recovery "
                    "story in an inline waiver comment",
                    first[1]))
            if persist_names and _reads_files(fn) and \
                    not _has_lock_guard(fn):
                emit(Finding(
                    PASS, "rmw-unguarded", rel, fn.qual,
                    sorted(set(persist_names))[0],
                    "read-modify-write of artifact "
                    f"{sorted(set(persist_names))[0]!r} outside any "
                    "lock/O_EXCL guard: concurrent writers interleave "
                    "to a torn logical state even though each write "
                    "is atomic",
                    fn.node.lineno))
        return findings
