"""Shared machinery for the thread-safety pass family (round 13):
thread-context derivation over the PR 4 call graph, the threadctx.py
ownership-registry parser, and attribute-mutation site collection with
lexical lockset tracking.

THREAD CONTEXTS. Every project function is assigned the set of thread
contexts it is statically reachable from:

- ``loop``          — `async def` bodies, plus anything they call
  synchronously, plus functions posted to a loop via
  `call_soon_threadsafe` / `run_coroutine_threadsafe` /
  `threadctx.call_threadsafe`;
- ``worker:<qual>`` — one context per thread-submission ROOT: a
  function handed to `asyncio.to_thread`, `run_in_executor`,
  `executor.submit` (the ops/staging.py pool, the per-device dispatch
  streams in ops/overlap.py), or `threading.Thread(target=...)`,
  plus everything it calls synchronously. Each root is its OWN
  context: two different submissions may run on different pool
  threads concurrently;
- ``atexit``        — `atexit.register` / `signal.signal` targets
  (shutdown runs them on whatever thread the interpreter exits on).

Propagation is a fixed point over resolvable, unwrapped call edges
(wrapped calls execute in the callee's submitted context, which the
seeding already covers). A function reachable from two or more
distinct contexts is MULTI-CONTEXT; a class whose attribute mutations
span two or more contexts is what the ownership registry exists to
govern.

This is a best-effort over-approximation in exactly the PR 4 spirit:
dynamic dispatch the resolver cannot see is covered by the runtime
twin (spacedrive_tpu/threadctx.py armed with the sanitizer).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..core import FuncInfo, Project, dotted, own_body_walk

LOOP = "loop"
ATEXIT = "atexit"

CENTRAL = "spacedrive_tpu/threadctx.py"

# Calls whose function-reference ARGUMENTS run on a worker thread.
_WORKER_SUBMITTERS = {"to_thread", "run_in_executor", "submit"}
# Calls whose function-reference arguments run on the EVENT LOOP
# (posted from any thread) — the sanctioned hand-off shapes.
LOOP_POSTERS = {"call_soon_threadsafe", "run_coroutine_threadsafe",
                "call_threadsafe", "call_soon", "call_later"}
# Shutdown-hook registrars.
_SHUTDOWN_REGISTRARS = {"atexit.register", "signal.signal"}


def _fn_key(fn: FuncInfo) -> str:
    return f"{fn.src.relpath}::{fn.qual}"


def _memo(project: Project, key, build):
    """Per-Project memo for the pure whole-tree analyses this module
    provides: three passes share a lint run's Project, and re-deriving
    the context fixed point or the mutation-site sweep per pass would
    double the analyzer's wall time for identical results. The cache
    rides the Project instance, so a fresh load (tests, --changed
    re-index) naturally starts cold."""
    cache = getattr(project, "_threads_memo", None)
    if cache is None:
        cache = {}
        project._threads_memo = cache
    if key not in cache:
        cache[key] = build()
    return cache[key]


def _callable_args(call: ast.Call) -> List[ast.AST]:
    out = list(call.args)
    out.extend(kw.value for kw in call.keywords)
    return out


def thread_contexts(project: Project) -> Dict[str, Set[str]]:
    """func key ("relpath::qual") → context-label set. Functions
    reachable from no known root map to an empty set (ambient
    bench/test drivers — single-threaded by construction). Memoized
    per Project (shared by all three thread passes)."""
    return _memo(project, "contexts", lambda: _thread_contexts(project))


def _thread_contexts(project: Project) -> Dict[str, Set[str]]:
    idx = project.index
    contexts: Dict[str, Set[str]] = {_fn_key(f): set()
                                     for f in idx.funcs}

    # -- seeds -------------------------------------------------------------
    for fn in idx.funcs:
        if fn.is_async:
            contexts[_fn_key(fn)].add(LOOP)
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            if last in _WORKER_SUBMITTERS:
                for arg in _callable_args(node):
                    ad = dotted(arg)
                    if ad is None:
                        continue
                    target = idx.resolve(fn, ad)
                    if target is not None and not target.is_async:
                        contexts[_fn_key(target)].add(
                            f"worker:{target.qual}")
            elif last in LOOP_POSTERS:
                for arg in _callable_args(node):
                    ad = dotted(arg)
                    if ad is None:
                        continue
                    target = idx.resolve(fn, ad)
                    if target is not None:
                        contexts[_fn_key(target)].add(LOOP)
            elif d in _SHUTDOWN_REGISTRARS:
                for arg in _callable_args(node):
                    ad = dotted(arg)
                    if ad is None:
                        continue
                    target = idx.resolve(fn, ad)
                    if target is not None:
                        contexts[_fn_key(target)].add(ATEXIT)
            elif last == "Thread" and d.split(".")[0] in ("threading",
                                                          "Thread"):
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    ad = dotted(kw.value)
                    if ad is None:
                        continue
                    target = idx.resolve(fn, ad)
                    if target is not None:
                        contexts[_fn_key(target)].add(
                            f"worker:{target.qual}")

    # -- fixed-point propagation over resolvable sync call edges -----------
    changed = True
    while changed:
        changed = False
        for fn in idx.funcs:
            src_ctx = contexts[_fn_key(fn)]
            if not src_ctx:
                continue
            for site in fn.calls:
                if site.wrapped:
                    continue  # executes in a submitted context
                callee = idx.resolve(fn, site.name)
                if callee is None:
                    continue
                if callee.is_async:
                    # A worker cannot RUN an async callee by calling
                    # it; a loop context calling it is already loop.
                    continue
                dst = contexts[_fn_key(callee)]
                add = src_ctx - dst
                if add:
                    dst |= add
                    changed = True
    return contexts


# -- ownership-registry parsing (AST: the linted tree is never imported) ----

_KIND_FACTORIES = {"loop_only", "single_thread", "guarded_by",
                   "atomic_counter", "immutable_after_init"}


def _parse_attr_contract(node: ast.AST) -> Optional[Tuple[str,
                                                          Optional[str]]]:
    """(kind, lock) for a `guarded_by("x")` / `loop_only()` value."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted(node.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    if last not in _KIND_FACTORIES:
        return None
    lock = None
    if last == "guarded_by" and node.args and \
            isinstance(node.args[0], ast.Constant):
        lock = str(node.args[0].value)
    return last, lock


def declared_owners_from_tree(tree: ast.Module) -> Dict[str, Dict]:
    """name → {site, attrs: {attr: (kind, lock)}, lineno} for every
    literal `declare_owner(...)` call in one module AST."""
    out: Dict[str, Dict] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        d = dotted(node.func)
        if d is None or d.rsplit(".", 1)[-1] != "declare_owner":
            continue
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            continue
        spec = {"site": "", "attrs": {}, "lineno": node.lineno}
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            spec["site"] = str(node.args[1].value)
        attrs_node = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg == "site" and isinstance(kw.value, ast.Constant):
                spec["site"] = str(kw.value.value)
            if kw.arg == "attrs":
                attrs_node = kw.value
        if isinstance(attrs_node, ast.Dict):
            for k, v in zip(attrs_node.keys, attrs_node.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                parsed = _parse_attr_contract(v)
                if parsed is not None:
                    spec["attrs"][k.value] = parsed
        out[name.value] = spec
    return out


def declared_owners(root: str, project: Project) -> Dict[str, Dict]:
    """The ownership table: the central registry plus any declarations
    inside the analyzed files themselves (how the per-pass fixtures
    self-declare). Memoized per Project."""
    return _memo(project, ("owners", root),
                 lambda: _declared_owners(root, project))


def _declared_owners(root: str, project: Project) -> Dict[str, Dict]:
    out: Dict[str, Dict] = {}
    in_project = {src.relpath for src in project.files}
    if CENTRAL not in in_project:
        path = os.path.join(root, CENTRAL)
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
            out.update(declared_owners_from_tree(tree))
        except (OSError, SyntaxError):
            pass
    for src in project.files:
        out.update(declared_owners_from_tree(src.tree))
    return out


def owners_by_class(declared: Dict[str, Dict]) -> Dict[str, Dict]:
    """ClassName → owner spec (class names are unique by registry
    construction — threadctx.declare_owner enforces it)."""
    out: Dict[str, Dict] = {}
    for name, spec in declared.items():
        site = spec.get("site", "")
        if "::" in site:
            out[site.split("::", 1)[1]] = {"name": name, **spec}
    return out


def class_hierarchy(project: Project) -> Dict[str, List[str]]:
    """class name → base-class terminal names, project-wide (name-
    keyed: the registry enforces unique class names for its members,
    and for unregistered classes a rare collision only widens the
    contract lookup). Memoized per Project."""
    return _memo(project, "hierarchy",
                 lambda: _class_hierarchy(project))


def _class_hierarchy(project: Project) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = []
            for b in node.bases:
                d = dotted(b)
                if d is not None:
                    bases.append(d.rsplit(".", 1)[-1])
            out.setdefault(node.name, bases)
    return out


def effective_owner(cls_name: str, by_class: Dict[str, Dict],
                    hierarchy: Dict[str, List[str]]) -> Optional[Dict]:
    """The contract governing `cls_name`: its own declaration merged
    over its ancestors' (nearest wins — the runtime twin composes the
    same way down the MRO). None when no ancestor is declared."""
    merged_attrs: Dict[str, Tuple[str, Optional[str]]] = {}
    found: Optional[Dict] = None
    seen: Set[str] = set()

    def visit(name: str) -> None:
        nonlocal found
        if name in seen:
            return
        seen.add(name)
        for base in hierarchy.get(name, []):
            visit(base)
        spec = by_class.get(name)
        if spec is not None:
            found = spec
            merged_attrs.update(spec["attrs"])

    visit(cls_name)
    if found is None:
        return None
    return {**found, "attrs": merged_attrs}


# -- attribute-mutation site collection -------------------------------------

# `update` and `insert` are deliberately absent: `report.update(db)` /
# `db.insert(table, row)` are domain-object methods far more often
# than list/dict mutations in this tree — the ambiguity produced only
# false attributions (subscript writes still catch dict updates).
CONTAINER_MUTATORS = {
    "append", "appendleft", "extend", "remove", "clear",
    "add", "discard", "setdefault", "popitem",
}

_INIT_NAMES = {"__init__", "__post_init__", "__new__", "__set_name__"}


class MutationSite:
    """One write to `<receiver>.<attr>`: the receiver resolved to a
    class name (via self, a registered-class annotation, or a local
    construction), the lexical lockset held at the write, and whether
    it is an augmented (`+=`) update or a container mutation."""

    __slots__ = ("cls_name", "attr", "fn", "lineno", "locks", "aug",
                 "container", "in_init", "self_recv")

    def __init__(self, cls_name: str, attr: str, fn: FuncInfo,
                 lineno: int, locks: frozenset, aug: bool,
                 container: bool, in_init: bool, self_recv: bool):
        self.cls_name = cls_name
        self.attr = attr
        self.fn = fn
        self.lineno = lineno
        self.locks = locks
        self.aug = aug
        self.container = container
        self.in_init = in_init
        self.self_recv = self_recv


def _lock_of(expr: ast.AST) -> Optional[str]:
    from .lock_discipline import lock_name

    ln = lock_name(expr)
    if ln is not None:
        return ln
    # `with db.tx():` / `with sync.write_ops():` hold the database's
    # write lock for the whole block (store/db.py acquires
    # `_write_lock` on entry) — model it so guarded_by("_write_lock")
    # contracts are checkable at tx-protected mutation sites.
    if isinstance(expr, ast.Call):
        d = dotted(expr.func)
        if d is not None and d.rsplit(".", 1)[-1] in ("tx",
                                                      "write_ops"):
            return "_write_lock"
    return None


def _annotation_classes(fn: FuncInfo, known: Set[str]) -> Dict[str, str]:
    """param name → class name, for parameters annotated with a known
    (registered or project) class — `stats: Optional[PipelineStats]`
    resolves `stats.h2d_bytes += ...` to PipelineStats."""
    out: Dict[str, str] = {}
    args_node = getattr(fn.node, "args", None)
    if args_node is None:
        return out
    every = (list(args_node.posonlyargs) + list(args_node.args)
             + list(args_node.kwonlyargs))
    for a in every:
        if a.annotation is None:
            continue
        for sub in ast.walk(a.annotation):
            if isinstance(sub, ast.Name) and sub.id in known:
                out[a.arg] = sub.id
                break
            if isinstance(sub, ast.Attribute) and sub.attr in known:
                out[a.arg] = sub.attr
                break
    return out


def _local_constructions(fn: FuncInfo, known: Set[str]) -> Dict[str, str]:
    """local name → class name for `x = KnownClass(...)` in this body."""
    out: Dict[str, str] = {}
    for node in own_body_walk(fn.node):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        d = dotted(node.value.func)
        if d is None:
            continue
        last = d.rsplit(".", 1)[-1]
        if last not in known:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = last
    return out


def collect_mutations(project: Project,
                      known_classes: Set[str]) -> List[MutationSite]:
    """Every attribute-mutation site attributable to a class: `self.x`
    writes inside methods, and `recv.x` writes where `recv` is a
    parameter annotated with — or a local constructed from — a class
    in `known_classes`. Tracks the lexical with-lock stack so contract
    checks can test guard coverage. Memoized per Project + known set
    (shared-mutation and guard-consistency sweep the same tree)."""
    return _memo(project, ("mutations", frozenset(known_classes)),
                 lambda: _collect_mutations(project, known_classes))


def _collect_mutations(project: Project,
                       known_classes: Set[str]) -> List[MutationSite]:
    sites: List[MutationSite] = []
    for fn in project.index.funcs:
        ann = _annotation_classes(fn, known_classes)
        local = _local_constructions(fn, known_classes)
        in_init = fn.name in _INIT_NAMES

        def resolve_recv(expr: ast.AST) -> Optional[Tuple[str, str,
                                                          bool]]:
            """(cls_name, attr, is_self) for `<recv>.<attr>` nodes."""
            if not isinstance(expr, ast.Attribute):
                return None
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fn.cls is not None:
                    return fn.cls, expr.attr, True
                cls = ann.get(base.id) or local.get(base.id)
                if cls is not None:
                    return cls, expr.attr, False
            return None

        def note(node: ast.AST, locks: Tuple[str, ...]) -> None:
            lockset = frozenset(locks)
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    leaves = (tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt])
                    for leaf in leaves:
                        container = False
                        if isinstance(leaf, ast.Subscript):
                            leaf = leaf.value
                            container = True
                        r = resolve_recv(leaf)
                        if r is None:
                            continue
                        cls_name, attr, is_self = r
                        sites.append(MutationSite(
                            cls_name, attr, fn, node.lineno, lockset,
                            isinstance(node, ast.AugAssign),
                            container, in_init, is_self))
            elif isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is None or d.split(".")[-1] not in \
                        CONTAINER_MUTATORS or \
                        not isinstance(node.func, ast.Attribute):
                    return
                r = resolve_recv(node.func.value)
                if r is None:
                    return
                cls_name, attr, is_self = r
                sites.append(MutationSite(
                    cls_name, attr, fn, node.lineno, lockset,
                    False, True, in_init, is_self))
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    leaf = tgt
                    container = False
                    if isinstance(leaf, ast.Subscript):
                        leaf = leaf.value
                        container = True
                    r = resolve_recv(leaf)
                    if r is None:
                        continue
                    cls_name, attr, is_self = r
                    sites.append(MutationSite(
                        cls_name, attr, fn, node.lineno, lockset,
                        False, container, in_init, is_self))

        def walk(nodes, locks: Tuple[str, ...]) -> None:
            for node in nodes:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested bodies run in their own context
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    # async with asyncio locks guard contracts too.
                    new = list(locks)
                    for item in node.items:
                        ln = _lock_of(item.context_expr)
                        if ln is not None:
                            new.append(ln)
                    walk(node.body, tuple(new))
                    continue
                note(node, locks)
                walk(list(ast.iter_child_nodes(node)), locks)

        walk(fn.node.body, ())
    return sites
