"""Pass: tmp-hygiene — scratch space dies with its owner.

Every `tempfile.mkdtemp` before this pass had a happy-path `rmtree`
and an error path that leaked: sync_bench left three `sync-*-bench-`
trees per crashed run, a failed perf_smoke parked multi-GB corpora in
/tmp until the machine noticed. Scratch space must be cleaned by
CONSTRUCTION — `persist.scratch("name")` (a declared artifact whose
context manager rmtrees in `finally`), a `TemporaryDirectory`
context, or an explicit `try/finally` — not by remembering to call
rmtree on the one path the author tested.

Scope: the whole lint tree (spacedrive_tpu/ + tools/) — bench
harnesses are where the leaks lived.

Codes:

- ``tmp-no-cleanup``: `mkdtemp`/`mkstemp`/`NamedTemporaryFile(
  delete=False)` in a function with NO cleanup call at all (no
  rmtree/remove/unlink referencing anything).
- ``tmp-leak-on-error``: cleanup exists but only on the straight-line
  path — nothing in a `finally`, an except handler, or a `with`
  context guarantees it when the function raises.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "tmp-hygiene"

_MAKERS = {"mkdtemp", "mkstemp"}
_CLEANERS = {"rmtree", "remove", "unlink", "rmdir", "scratch",
             "cleanup"}


def _tmp_maker(call: ast.Call, d: str) -> str:
    last = d.rsplit(".", 1)[-1]
    if last in _MAKERS:
        return last
    if last == "NamedTemporaryFile":
        for kw in call.keywords:
            if kw.arg == "delete" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is False:
                return last
    return ""


def _mentions_cleaner(node: ast.AST) -> bool:
    """A cleanup callable anywhere under `node` — called directly
    (`shutil.rmtree(tmp)`) or passed as a reference
    (`to_thread(shutil.rmtree, tmp)`)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _CLEANERS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _CLEANERS:
            return True
    return False


def _guarded_cleanup(fn) -> bool:
    """Cleanup guaranteed on error paths: a cleaner inside any
    `finally:`/`except:` of the function's own body, or the maker's
    result managed by a `with` block (context managers clean up in
    __exit__)."""
    for node in own_body_walk(fn.node):
        if isinstance(node, ast.Try):
            for blk in (node.finalbody, *[h.body for h in node.handlers]):
                if any(_mentions_cleaner(stmt) for stmt in blk):
                    return True
    return False


class TmpHygienePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            rel = fn.src.relpath
            makers = []
            with_managed: Set[int] = set()
            for node in own_body_walk(fn.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        cm = item.context_expr
                        if isinstance(cm, ast.Call):
                            with_managed.add(id(cm))
            for site in fn.calls:
                maker = _tmp_maker(site.node, site.name)
                if maker and id(site.node) not in with_managed:
                    makers.append((maker, site.node.lineno))
            if not makers:
                continue
            any_cleanup = _mentions_cleaner(fn.node)
            guarded = _guarded_cleanup(fn)
            for maker, lineno in makers:
                if guarded:
                    continue
                if not any_cleanup:
                    emit(Finding(
                        PASS, "tmp-no-cleanup", rel, fn.qual, maker,
                        f"{maker} with no cleanup anywhere in the "
                        "function: every crashed run leaks a tree — "
                        "use persist.scratch(\"<artifact>\") or a "
                        "try/finally rmtree",
                        lineno))
                else:
                    emit(Finding(
                        PASS, "tmp-leak-on-error", rel, fn.qual, maker,
                        f"{maker} cleaned only on the straight-line "
                        "path: an exception before the cleanup leaks "
                        "the tree — move the rmtree into a finally "
                        "(or use persist.scratch)",
                        lineno))
        return findings
