"""Pass: flag-registry — every SDTPU_* flag declared and read centrally.

`spacedrive_tpu/flags.py` is the single source of truth for the
engine's environment-flag surface: name, default, parser, docstring,
and the generated README table. This pass enforces the two halves of
that contract over `spacedrive_tpu/` and `tools/`:

- `undeclared-flag`  — an `SDTPU_*` string literal that no `declare()`
  in flags.py covers (typo'd flag names silently no-op at runtime;
  here they fail the build);
- `environ-read`     — a direct READ of an SDTPU flag from the
  environment (`os.environ.get`, `os.getenv`, `os.environ[...]` in a
  load context) anywhere outside flags.py. Writes are fine — benches
  and tests toggle flags via `os.environ[...] = ...` / `setdefault` /
  `pop`, and reads go live through `flags.get()` so the toggles still
  take effect.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from ..core import Finding, Project, dotted

PASS = "flag-registry"
FLAG_RE = re.compile(r"^SDTPU_[A-Z0-9_]+$")
CENTRAL = "spacedrive_tpu/flags.py"


def declared_flags(root: str) -> Set[str]:
    """Flag names from `declare("SDTPU_X", ...)` calls in flags.py."""
    path = os.path.join(root, CENTRAL)
    out: Set[str] = set()
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) == "declare" \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.add(arg.value)
    return out


def _flag_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and FLAG_RE.match(node.value):
        return node.value
    return None


class FlagRegistryPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_flags(project.root)
        findings: List[Finding] = []
        for src in project.files:
            is_central = src.relpath == CENTRAL
            # literals in write-position subscripts/calls are collected
            # so the same literal is not double-reported
            reported: Set[str] = set()
            for node in ast.walk(src.tree):
                flag = _flag_literal(node)
                if flag is not None and flag not in declared \
                        and not is_central and flag not in reported:
                    reported.add(flag)
                    findings.append(Finding(
                        PASS, "undeclared-flag", src.relpath, "", flag,
                        f"flag {flag!r} is not declared in "
                        f"spacedrive_tpu/flags.py (typo, or declare it)",
                        node.lineno))
                if is_central:
                    continue
                read = self._environ_read(node)
                if read is not None:
                    findings.append(Finding(
                        PASS, "environ-read", src.relpath, "", read,
                        f"direct environment read of {read!r} — go "
                        f"through flags.get()/flags.raw() so the "
                        f"registry stays authoritative", node.lineno))
        return findings

    @staticmethod
    def _environ_read(node: ast.AST) -> Optional[str]:
        # os.environ.get("SDTPU_X", ...) / os.getenv("SDTPU_X")
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d in ("os.environ.get", "os.getenv", "environ.get") \
                    and node.args:
                return _flag_literal(node.args[0])
            return None
        # os.environ["SDTPU_X"] in a LOAD context (a store/del is a
        # write — allowed)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            base = dotted(node.value)
            if base in ("os.environ", "environ"):
                return _flag_literal(node.slice)
        return None
