"""Pass: task-lifecycle — every background task must have an owner.

`asyncio` gives spawned tasks NO structure: `create_task` returns a
reference the event loop only holds weakly, so a task nobody stores
can be garbage-collected (and with it, silently cancelled) mid-flight
— the `locations/watcher.py` dirty-scan bug this pass encodes. The
discipline is structured concurrency (`spacedrive_tpu/tasks.py`):
either the result is stored on an owner (and awaited/cancelled at its
lifecycle edge) or the spawn goes through the supervisor's
`tasks.spawn(name, coro, owner=...)`, which keeps a strong reference,
observes the outcome, and is reaped at `Node.shutdown()`.

Rules:

- ``dropped-task`` — a `create_task` / `ensure_future` whose result is
  discarded (the call IS an expression statement). A supervisor
  `spawn(...)` is exempt: the registry holds the reference.
- ``deprecated-get-event-loop`` — any `asyncio.get_event_loop()` call:
  inside a running loop it aliases `get_running_loop()` (use that);
  outside one it silently CREATES a loop the caller never runs —
  both shapes hid the watcher bug.
- ``spawn-in-loop`` — a spawn (including supervisor `spawn`) inside a
  `for`/`while` body whose task is never awaited in the function
  (directly or via `asyncio.wait`/`gather` on the stored name): an
  unbounded task storm with no backpressure. The jobs worker's
  step/command pair passes because both land in `asyncio.wait`.

The supervisor module itself (`spacedrive_tpu/tasks.py`) is exempt —
it is the one legitimate home of a raw `create_task`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "task-lifecycle"

_SPAWN_LAST = {"create_task", "ensure_future"}
_SUPERVISOR_LAST = {"spawn"}
SUPERVISOR_PATH = "spacedrive_tpu/tasks.py"


def _spawn_kind(call: ast.Call) -> str:
    """'raw' | 'supervised' | '' for a call node. Dynamic receivers
    (`asyncio.get_event_loop().create_task(...)` — a call-chained
    receiver `dotted()` cannot name) still classify by the terminal
    attribute: that chain was exactly the watcher.py dropped-task bug."""
    f = call.func
    d = dotted(f)
    last = d.rsplit(".", 1)[-1] if d else (
        f.attr if isinstance(f, ast.Attribute) else "")
    if last in _SPAWN_LAST:
        return "raw"
    if last in _SUPERVISOR_LAST:
        return "supervised"
    return ""


def _spawn_ident(call: ast.Call) -> str:
    d = dotted(call.func)
    if d:
        return d
    if isinstance(call.func, ast.Attribute):
        return f"<dynamic>.{call.func.attr}"
    return "<spawn>"


def _subtree_skip_defs(node: ast.AST):
    """Walk a subtree, not descending into nested function bodies
    (their code runs at another time)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class TaskLifecyclePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            if fn.src.relpath == SUPERVISOR_PATH:
                continue
            self._check_fn(fn, emit)
        # Module level: get_event_loop / dropped spawns outside any def.
        for src in project.files:
            if src.relpath == SUPERVISOR_PATH:
                continue
            for node in _subtree_skip_defs(src.tree):
                if isinstance(node, ast.Call) and \
                        dotted(node.func) == "asyncio.get_event_loop":
                    emit(Finding(
                        PASS, "deprecated-get-event-loop", src.relpath,
                        "", "asyncio.get_event_loop",
                        "asyncio.get_event_loop() is deprecated: use "
                        "get_running_loop() (or tasks.spawn)",
                        node.lineno))
                if isinstance(node, ast.Expr) and \
                        isinstance(node.value, ast.Call) and \
                        _spawn_kind(node.value) == "raw":
                    d = _spawn_ident(node.value)
                    emit(Finding(
                        PASS, "dropped-task", src.relpath, "", d,
                        f"`{d}` result discarded: the loop holds tasks "
                        "weakly — store it on an owner or use "
                        "tasks.spawn",
                        node.lineno))
        return findings

    def _check_fn(self, fn, emit) -> None:
        rel = fn.src.relpath
        # Names awaited anywhere in the function (directly, or inside
        # an `await asyncio.wait({...})` / gather expression).
        awaited_names: Set[str] = set()
        # id(call) → assigned target names, for spawn calls.
        assigned: Dict[int, Set[str]] = {}
        dropped_ids: Set[int] = set()
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Await):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        awaited_names.add(sub.id)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _spawn_kind(node.value):
                names = set()
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
                        elif isinstance(sub, ast.Attribute):
                            names.add(sub.attr)
                assigned[id(node.value)] = names
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                kind = _spawn_kind(node.value)
                if kind == "raw":
                    dropped_ids.add(id(node.value))
                    d = _spawn_ident(node.value)
                    emit(Finding(
                        PASS, "dropped-task", rel, fn.qual, d,
                        f"`{d}` result discarded: the loop holds tasks "
                        "weakly (GC may cancel it mid-flight) — store "
                        "it on an owner or use tasks.spawn",
                        node.value.lineno))
            if isinstance(node, ast.Call) and \
                    dotted(node.func) == "asyncio.get_event_loop":
                emit(Finding(
                    PASS, "deprecated-get-event-loop", rel, fn.qual,
                    "asyncio.get_event_loop",
                    "asyncio.get_event_loop() is deprecated: use "
                    "get_running_loop() (or pass the loop / use "
                    "tasks.spawn)",
                    node.lineno))
        # Spawns inside loops: unbounded unless the stored task is
        # awaited somewhere in this function.
        for loop_node in own_body_walk(fn.node):
            if not isinstance(loop_node, (ast.For, ast.While,
                                          ast.AsyncFor)):
                continue
            for node in _subtree_skip_defs(loop_node):
                if not (isinstance(node, ast.Call) and _spawn_kind(node)):
                    continue
                if id(node) in dropped_ids:
                    continue  # already reported as dropped-task
                names = assigned.get(id(node), set())
                if names and names & awaited_names:
                    continue  # bounded: the task is awaited
                d = _spawn_ident(node)
                emit(Finding(
                    PASS, "spawn-in-loop", rel, fn.qual, f"loop:{d}",
                    f"`{d}` inside a loop with no await on the spawned "
                    "task: an unbounded task storm — await it (or a "
                    "window of them) inside the loop",
                    node.lineno))
