"""Pass: cancellation-safety — cancellation must flow, never vanish.

Structured shutdown (tasks.reap, Node.shutdown) works by CANCELLING
tasks and awaiting them; anything that swallows `CancelledError` turns
a bounded shutdown into a hang (or an orphaned task the supervisor
then reports). Four shapes, each observed in this tree before the
pass landed:

- ``swallow-cancel`` — a handler that catches `CancelledError`
  *by accident* — bare ``except:``, ``except BaseException``, or the
  conflated ``except (asyncio.CancelledError, Exception)`` — around
  an awaiting try-body, without re-raising. A LONE
  ``except asyncio.CancelledError`` is the legitimate reap idiom and
  passes (better: `tasks.cancel_and_gather`, which also keeps the
  caller's own cancellation alive).
- ``await-in-finally`` — an `await` in a ``finally:`` block that is
  not wrapped in `asyncio.shield` / `asyncio.wait_for` /
  `with_timeout`: when the block runs because the task is being
  cancelled, that await is the task's cleanup budget — unshielded and
  unbounded, it either dies mid-cleanup on the next cancel or hangs
  shutdown forever.
- ``no-cancel-point`` — a ``while True:`` in an `async def` whose body
  contains no await (and no break/return): `task.cancel()` can never
  be delivered; the reap declares it an orphan every time.
- ``dropped-exception-callback`` — `add_done_callback` with a
  container method (`set.discard` & co.) or a lambda that ignores its
  task argument: the task's exception is never retrieved, surfacing
  (if ever) as an interpreter-exit log line. The supervisor's
  done-callback is the fix (`tasks.spawn` observes every outcome).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "cancellation-safety"

_CONTAINER_CALLBACKS = {"discard", "remove", "append", "add", "pop",
                        "clear"}
_FINALLY_WRAPPERS = {"shield", "wait_for", "with_timeout"}


def _subtree_skip_defs(node: ast.AST):
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _stmts_walk(stmts):
    for s in stmts:
        yield s
        yield from _subtree_skip_defs(s)


def _handler_shape(handler: ast.ExceptHandler) -> Optional[str]:
    """The flaggable catch shape, or None if the handler cannot
    swallow a cancellation by accident."""
    t = handler.type
    if t is None:
        return "bare"
    def last(n):
        d = dotted(n)
        return d.rsplit(".", 1)[-1] if d else ""
    if last(t) == "BaseException":
        return "BaseException"
    if isinstance(t, ast.Tuple):
        lasts = {last(el) for el in t.elts}
        if "BaseException" in lasts:
            return "BaseException"
        if "CancelledError" in lasts and len(lasts) > 1:
            # the conflated reap idiom: CancelledError lumped with
            # Exception (or anything else) in one silencing handler
            return "+".join(sorted(lasts))
    return None


def _has_raise(stmts) -> bool:
    return any(isinstance(n, ast.Raise) for n in _stmts_walk(stmts))


def _has_await(stmts) -> bool:
    return any(isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
               for n in _stmts_walk(stmts))


class CancellationSafetyPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            rel = fn.src.relpath
            if fn.is_async:
                self._check_async(fn, rel, emit)
            for node in own_body_walk(fn.node):
                if isinstance(node, ast.Call):
                    self._check_callback(node, rel, fn.qual, emit)
        return findings

    def _check_async(self, fn, rel: str, emit) -> None:
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Try):
                body_awaits = _has_await(node.body)
                for handler in node.handlers:
                    shape = _handler_shape(handler)
                    if shape and body_awaits and \
                            not _has_raise(handler.body):
                        emit(Finding(
                            PASS, "swallow-cancel", rel, fn.qual,
                            f"except:{shape}",
                            f"`except {shape}` around an awaiting body "
                            "swallows CancelledError — catch "
                            "CancelledError alone (reap idiom / "
                            "tasks.cancel_and_gather) or re-raise",
                            handler.lineno))
                for sub in _stmts_walk(node.finalbody):
                    if not isinstance(sub, ast.Await):
                        continue
                    v = sub.value
                    wrapped = (isinstance(v, ast.Call) and
                               (dotted(v.func) or "").rsplit(".", 1)[-1]
                               in _FINALLY_WRAPPERS)
                    if not wrapped:
                        ident = (dotted(v.func) or "await"
                                 ) if isinstance(v, ast.Call) else "await"
                        emit(Finding(
                            PASS, "await-in-finally", rel, fn.qual,
                            f"finally:{ident}",
                            "unshielded await in `finally`: during "
                            "cancellation this is unbounded cleanup — "
                            "wrap in asyncio.shield (or a timeout)",
                            sub.lineno))
            if isinstance(node, ast.While) and \
                    isinstance(node.test, ast.Constant) and node.test.value:
                body = list(_stmts_walk(node.body))
                has_point = any(isinstance(
                    n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
                    for n in body)
                has_exit = any(isinstance(n, (ast.Break, ast.Return))
                               for n in body)
                if not has_point and not has_exit:
                    emit(Finding(
                        PASS, "no-cancel-point", rel, fn.qual,
                        "while-true",
                        "`while True` with no await/break in an async "
                        "function: cancellation can never be "
                        "delivered — add an await (e.g. sleep(0))",
                        node.lineno))

    def _check_callback(self, call: ast.Call, rel: str, qual: str,
                        emit) -> None:
        d = dotted(call.func)
        if d is None or d.rsplit(".", 1)[-1] != "add_done_callback":
            return
        if not call.args:
            return
        cb = call.args[0]
        if isinstance(cb, ast.Attribute) and \
                cb.attr in _CONTAINER_CALLBACKS:
            emit(Finding(
                PASS, "dropped-exception-callback", rel, qual,
                dotted(cb) or cb.attr,
                f"done-callback `{dotted(cb) or cb.attr}` drops the "
                "task outcome: a failed task's exception is never "
                "retrieved — use tasks.spawn (supervised) or a "
                "callback that checks task.exception()",
                call.lineno))
        elif isinstance(cb, ast.Lambda) and cb.args.args:
            param = cb.args.args[0].arg
            used = any(isinstance(n, ast.Name) and n.id == param
                       for n in ast.walk(cb.body))
            if not used:
                emit(Finding(
                    PASS, "dropped-exception-callback", rel, qual,
                    f"lambda:{param}-unused",
                    "done-callback lambda ignores its task argument: "
                    "the task outcome (and any exception) is dropped",
                    call.lineno))
