"""Pass: schema-parity — statement contracts match the model registry.

Schema drift is caught at LINT time, not at the first production
query: every declared statement's SQL is cross-validated against
store/models.py (parsed by AST, like crdt-parity). Codes:

- `unknown-table`  — a table in the declaration's `tables=` or parsed
  from its SQL that no registered model (or SQLite internal) defines.
- `tables-drift`   — the declared `tables=` set disagrees with the
  tables parsed from the SQL text (the declaration IS the inventory;
  a wrong entry poisons --sql-table and the health attribution).
- `unknown-column` — an identifier in the SQL that is neither a
  column of the statement's tables, a table name/alias, a result
  alias, nor a SQL keyword/function. A column dropped from models.py
  turns into a finding here instead of an OperationalError later.
- `unindexed-filter` — a WHERE/ON filter over a column of a LARGE
  table (statements.py LARGE_TABLES) with no pk/unique/index/
  lazy_index whose leading column covers it. Advisory — bounded
  scans waive inline/baseline with the measured reason.

Shapes participate where they can: `{i}`/`{w}` slots render as an
ignorable sentinel, so their constant parts are still checked.
"""

from __future__ import annotations

from typing import List, Set

from ..core import Finding, Project
from . import _sql

PASS = "schema-parity"

EXTERNAL_TABLES = {"sqlite_master"}
# mirrors statements.py LARGE_TABLES (drift pinned by test)
LARGE_TABLES = {
    "file_path", "object", "shared_operation", "shared_op_blob",
    "relation_operation", "media_data", "near_dup_pair", "job_scratch",
}


class SchemaParityPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        info = _sql.models_schema(project.root)
        if not info.columns:
            return []
        findings: List[Finding] = []
        decls = _sql.project_decls(project)
        # Judge only declarations whose source is part of THIS run's
        # scope: fixture/incremental runs load the central registry
        # for name resolution but must not re-report (or re-suppress)
        # its findings without its suppression markers in view.
        in_scope = {f.relpath for f in project.files}
        for d in decls.values():
            if d.path in in_scope:
                self._check(d, info, findings)
        return findings

    def _check(self, d: _sql.Decl, info, findings: List[Finding]):
        sql = d.sql.replace("{i}", _sql.DYN).replace("{w}", _sql.DYN)
        known = set(info.columns) | EXTERNAL_TABLES
        parsed = _sql.parse_tables(sql)
        for t in set(d.tables) | parsed:
            if t not in known and t != _sql.DYN:
                findings.append(Finding(
                    PASS, "unknown-table", d.path, "", f"{d.name}:{t}",
                    f"statement {d.name!r} references table {t!r} "
                    "which is not in the model registry", d.lineno))
        real_parsed = {t for t in parsed if t in known}
        if not d.shape and real_parsed and \
                real_parsed != set(d.tables) & known:
            missing = real_parsed - set(d.tables)
            extra = set(d.tables) - real_parsed
            if missing or extra:
                findings.append(Finding(
                    PASS, "tables-drift", d.path, "", d.name,
                    f"statement {d.name!r}: declared tables "
                    f"{sorted(d.tables)} vs SQL tables "
                    f"{sorted(real_parsed)}", d.lineno))
        self._check_columns(d, sql, info, findings)
        self._check_filters(d, sql, info, findings)

    def _check_columns(self, d, sql, info, findings):
        idents, aliases, result_aliases = _sql.parse_identifiers(sql)
        tables = {t for t in (set(d.tables) | _sql.parse_tables(sql))
                  if t in info.columns}
        col_pool: Set[str] = {"rowid", "*", _sql.DYN}
        for t in tables:
            col_pool |= info.columns[t]
        # qualified refs: alias/table must resolve, column must belong
        for prefix, col in _sql.parse_qualified(sql):
            if prefix == _sql.DYN or col == _sql.DYN:
                continue
            table = aliases.get(prefix, prefix)
            if table in info.columns:
                if col not in info.columns[table] and col != "*" \
                        and col != "rowid":
                    findings.append(Finding(
                        PASS, "unknown-column", d.path, "",
                        f"{d.name}:{table}.{col}",
                        f"statement {d.name!r} references "
                        f"{table}.{col} but the model has no such "
                        "column", d.lineno))
        if d.shape and (_sql.DYN in sql or not tables):
            # a `{i}` table slot means the column universe is open —
            # only the qualified checks above can judge
            return
        if set(d.tables) & EXTERNAL_TABLES:
            # SQLite internals have no registered column set
            return
        known_non_columns = (set(info.columns) | EXTERNAL_TABLES
                             | set(aliases) | result_aliases
                             | {_sql.DYN})
        for tok in idents:
            if tok in known_non_columns or tok in col_pool:
                continue
            findings.append(Finding(
                PASS, "unknown-column", d.path, "",
                f"{d.name}:{tok}",
                f"statement {d.name!r} references {tok!r} which is "
                "no column of its tables "
                f"({sorted(tables) or 'none declared'})", d.lineno))

    def _check_filters(self, d, sql, info, findings):
        tables = {t for t in (set(d.tables) | _sql.parse_tables(sql))
                  if t in info.columns}
        large = tables & LARGE_TABLES
        if not large or d.verb != "read":
            return
        wcols = _sql.where_columns(sql)
        if not wcols:
            return
        for t in sorted(large):
            cols_here = wcols & info.columns[t]
            if not cols_here:
                continue
            if cols_here & info.indexed[t]:
                continue  # at least one indexed access path
            findings.append(Finding(
                PASS, "unindexed-filter", d.path, "",
                f"{d.name}:{t}",
                f"statement {d.name!r} filters large table {t} on "
                f"{sorted(cols_here)} with no declared or lazy index "
                "— a full scan at production scale", d.lineno))
