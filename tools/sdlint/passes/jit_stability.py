"""Pass: jit-stability — every jax.jit entry point under contract.

`spacedrive_tpu/ops/jit_registry.py` is the single source of truth for
the engine's jit surface: each entry point declares its trace budget,
static argnames, boundary dtypes, and shape-bucket policy, and binds
itself to the declaration with the `jit_registry.tracked("name")`
wrapper (which also does the runtime retrace accounting). This pass
enforces the binding and the compile-stability idioms around it:

- `unregistered-jit`  — a jit site (decorated def or `jax.jit(...)`
  assignment) with no `tracked(...)` binding: its trace behavior is
  invisible to both the registry and the retrace sanitizer;
- `unknown-jit-name`  — `tracked("x")` where no contract `x` exists;
- `call-time-jit`     — `jax.jit(fn)` constructed inside a function
  body whose contract is not a declared FACTORY: a fresh jit wrapper
  per call throws away the trace cache (the round-1..9 overlap.py:166
  shape — every calibration pause recompiled the kernel);
- `jit-in-loop`       — `jax.jit(...)` lexically inside a for/while:
  strictly worse than call-time construction;
- `static-args-mismatch` / `static-argnums` — the site's
  static_argnames drifted from the contract, or positional
  static_argnums are used (brittle under signature edits);
- `unhashable-static-arg` — a call site passes a list/dict/set literal
  for a declared static argname (TypeError at trace time, or a fresh
  trace per call if wrapped);
- `value-dependent-shape` — an argument to a registered jit callable
  is built inline with a `len(...)`-derived shape (`np.zeros(len(x))`
  at the boundary): Python-value-dependent shapes must go through the
  staging size classes / pow2 buckets, never raw lengths;
- `undeclared-donation` — a jit site passes `donate_argnums` /
  `donate_argnames` that its governing contract does not declare.
  Donation is a caller-visible semantic (the buffer is CONSUMED —
  reuse after the call raises), so it lives on the declared contract
  surface: the `donate_argnums` field of `declare_jit`. Declaring
  donation never forces it — undonated variants of the same contract
  stay legal (SDTPU_DONATE_BUFFERS=off).

The resolver is lexical by design: transfers and shapes that flow
through variables across functions are the runtime sanitizer's half
(retrace counters + transfer guard in spacedrive_tpu/sanitize.py).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, SourceFile, dotted

PASS = "jit-stability"
CENTRAL = "spacedrive_tpu/ops/jit_registry.py"

_CREATION_FNS = {"zeros", "empty", "ones", "full"}


def declared_contracts(root: str) -> Dict[str, dict]:
    """Contracts from `declare_jit(...)` calls in the central registry
    (AST — the linted tree is never imported)."""
    path = os.path.join(root, CENTRAL)
    out: Dict[str, dict] = {}
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "declare_jit" and node.args):
            continue
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            continue
        site = ""
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            site = str(node.args[1].value)
        c = {"site": site, "kind": "entry", "static_argnames": (),
             "host_transfer": False, "donate_argnums": ()}
        for kw in node.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                c["kind"] = kw.value.value
            elif kw.arg == "static_argnames":
                c["static_argnames"] = _str_tuple(kw.value)
            elif kw.arg == "host_transfer" \
                    and isinstance(kw.value, ast.Constant):
                c["host_transfer"] = bool(kw.value.value)
            elif kw.arg == "donate_argnums":
                c["donate_argnums"] = _int_tuple(kw.value)
        out[name.value] = c
    return out


def _str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                vals.append(el.value)
        return tuple(vals)
    return ()


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                vals.append(el.value)
        return tuple(vals)
    return ()


def _is_jit_expr(node: ast.AST) -> bool:
    return dotted(node) in ("jax.jit", "jit")


def _partial_jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The `functools.partial(jax.jit, ...)` form, or None."""
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d is not None and d.rsplit(".", 1)[-1] == "partial" \
                and node.args and _is_jit_expr(node.args[0]):
            return node
    return None


def _tracked_name(call: ast.AST) -> Optional[str]:
    """`jit_registry.tracked("name")` → "name"."""
    if isinstance(call, ast.Call):
        d = dotted(call.func)
        if d is not None and d.rsplit(".", 1)[-1] == "tracked" \
                and call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
    return None


def _static_args_of(deco: ast.AST) -> Tuple[Tuple[str, ...], bool]:
    """(static_argnames, uses_static_argnums) from a jit decorator."""
    call = _partial_jit_call(deco)
    if call is None and isinstance(deco, ast.Call) \
            and _is_jit_expr(deco.func):
        call = deco
    if call is None:
        return (), False
    names: Tuple[str, ...] = ()
    nums = False
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _str_tuple(kw.value)
        elif kw.arg == "static_argnums":
            nums = True
    return names, nums


def _donation_of(deco: ast.AST) -> Tuple[bool, Tuple[int, ...]]:
    """(site donates at all, parseable donated argnums) from a jit
    decorator or call. donate_argnames (string form) counts as
    donation with no parseable nums — the authorization check still
    applies, the subset check degrades to 'contract must declare
    donation'."""
    call = _partial_jit_call(deco)
    if call is None and isinstance(deco, ast.Call) \
            and _is_jit_expr(deco.func):
        call = deco
    if call is None:
        return False, ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return True, _int_tuple(kw.value)
        if kw.arg == "donate_argnames":
            return True, ()
    return False, ()


class _SiteVisitor(ast.NodeVisitor):
    """One file: jit defs/calls with qualnames, loop/tracked context."""

    def __init__(self, src: SourceFile, contracts: Dict[str, dict],
                 findings: List[Finding], bound_names: Dict[str, str]):
        self.src = src
        self.contracts = contracts
        self.findings = findings
        self.bound_names = bound_names  # callable name -> contract name
        self._stack: List[str] = []     # class/function qual parts
        self._fn_depth = 0
        self._loop_depth = 0
        self._factory_depth = 0         # inside a declared-factory def
        self._factory_contracts: List[dict] = []
        self._tracked_ctx: List[Optional[str]] = []

    # -- helpers ------------------------------------------------------

    def _qual(self, name: str = "") -> str:
        parts = self._stack + ([name] if name else [])
        return ".".join(parts)

    def _emit(self, code: str, qual: str, ident: str, msg: str,
              lineno: int) -> None:
        self.findings.append(Finding(
            PASS, code, self.src.relpath, qual, ident, msg, lineno))

    def _under_factory(self) -> bool:
        return self._factory_depth > 0

    def _contract_of_site(self, qual: str) -> Optional[dict]:
        site = f"{self.src.relpath}::{qual}"
        for c in self.contracts.values():
            if c["site"] == site:
                return c
        return None

    # -- structure ----------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        self._check_def(node, qual)
        contract = self._contract_of_site(qual)
        is_factory = contract is not None and contract["kind"] == "factory"
        self._stack.append(node.name)
        self._fn_depth += 1
        self._factory_depth += 1 if is_factory else 0
        if is_factory:
            self._factory_contracts.append(contract)
        self.generic_visit(node)
        if is_factory:
            self._factory_contracts.pop()
        self._factory_depth -= 1 if is_factory else 0
        self._fn_depth -= 1
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_For(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    # -- jit-decorated defs -------------------------------------------

    def _check_def(self, node, qual: str) -> None:
        jit_deco = None
        tracked = None
        for deco in node.decorator_list:
            if _is_jit_expr(deco) or _partial_jit_call(deco) is not None \
                    or (isinstance(deco, ast.Call)
                        and _is_jit_expr(deco.func)):
                jit_deco = deco
            name = _tracked_name(deco)
            if name is not None:
                tracked = name
        if jit_deco is None:
            return
        if self._loop_depth:
            self._emit(
                "jit-in-loop", qual, qual,
                "jit-decorated def inside a loop: a fresh traced "
                "function (and compile) per iteration", node.lineno)
        if tracked is None:
            if self._under_factory():
                return  # the factory's contract covers its inner jit
            self._emit(
                "unregistered-jit", qual, qual,
                f"jit entry point {qual!r} has no jit_registry.tracked "
                f"binding (declare a contract in {CENTRAL} and wrap "
                f"the jit with tracked(name))", node.lineno)
            return
        self._bind(tracked, node.name, qual, jit_deco, node.lineno)

    def _bind(self, name: str, callable_name: str, qual: str,
              jit_site: ast.AST, lineno: int) -> None:
        contract = self.contracts.get(name)
        if contract is None:
            self._emit(
                "unknown-jit-name", qual, name,
                f"tracked({name!r}) has no declared contract in "
                f"{CENTRAL}", lineno)
            return
        self.bound_names[callable_name] = name
        self._check_donation(jit_site, contract, name, qual, lineno)
        site_names, nums = _static_args_of(jit_site)
        if nums:
            self._emit(
                "static-argnums", qual, qual,
                "positional static_argnums are brittle under signature "
                "edits — use static_argnames", lineno)
        if tuple(site_names) != tuple(contract["static_argnames"]):
            self._emit(
                "static-args-mismatch", qual, name,
                f"site static_argnames {tuple(site_names)} != declared "
                f"{tuple(contract['static_argnames'])} for contract "
                f"{name!r}", lineno)

    # -- jax.jit(...) call expressions --------------------------------

    def visit_Call(self, node: ast.Call):
        tname = _tracked_name(node)
        if tname is None and isinstance(node.func, ast.Call):
            # the assignment form: tracked("name")(jax.jit(fn))
            tname = _tracked_name(node.func)
        if tname is not None:
            self._tracked_ctx.append(tname)
            self.generic_visit(node)
            self._tracked_ctx.pop()
            return
        if _is_jit_expr(node.func):
            self._check_jit_call(node)
        else:
            self._check_boundary_call(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # bind `x = tracked("name")(jax.jit(fn))` targets so call sites
        # of x get the boundary checks
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Call):
            name = _tracked_name(node.value.func)
            if name is not None and name in self.contracts:
                self.bound_names[node.targets[0].id] = name
        self.generic_visit(node)

    def _check_donation(self, jit_site: ast.AST, contract: Optional[dict],
                        cname: str, qual: str, lineno: int) -> None:
        donates, nums = _donation_of(jit_site)
        if not donates or contract is None:
            return
        declared = tuple(contract.get("donate_argnums") or ())
        if not declared or not set(nums) <= set(declared):
            self._emit(
                "undeclared-donation", qual, cname,
                f"jit site donates argnums {nums or '(dynamic)'} but "
                f"contract {cname!r} declares donate_argnums="
                f"{declared} — donation consumes the caller's buffers "
                f"and must be part of the declared surface (add "
                f"donate_argnums to the declare_jit in {CENTRAL})",
                lineno)

    def _check_jit_call(self, node: ast.Call) -> None:
        qual = self._qual()
        tracked = self._tracked_ctx[-1] if self._tracked_ctx else None
        # Donation authorization applies wherever the jit is built —
        # module level, factory body, or tracked assignment form.
        gov_name, gov = None, None
        if tracked is not None and tracked in self.contracts:
            gov_name, gov = tracked, self.contracts[tracked]
        elif self._factory_contracts:
            gov = self._factory_contracts[-1]
            gov_name = next((n for n, c in self.contracts.items()
                             if c is gov), "?")
        if gov is not None:
            self._check_donation(node, gov, gov_name, qual, node.lineno)
        if self._loop_depth:
            self._emit(
                "jit-in-loop", qual, qual or "module",
                "jax.jit(...) inside a loop: a fresh traced function "
                "(and compile) per iteration", node.lineno)
        if self._fn_depth == 0:
            # module level: fine if bound via tracked(...)
            if tracked is None:
                self._emit(
                    "unregistered-jit", qual, "module",
                    f"module-level jax.jit(...) without a "
                    f"jit_registry.tracked binding (declare it in "
                    f"{CENTRAL})", node.lineno)
            elif tracked not in self.contracts:
                self._emit(
                    "unknown-jit-name", qual, tracked,
                    f"tracked({tracked!r}) has no declared contract in "
                    f"{CENTRAL}", node.lineno)
            return
        if self._under_factory():
            return
        if tracked is not None and tracked in self.contracts \
                and self.contracts[tracked]["kind"] == "factory":
            return
        self._emit(
            "call-time-jit", qual, qual,
            "jax.jit(fn) constructed at call time: every invocation "
            "builds a fresh jit wrapper and retraces (cache the jit at "
            "module level, or declare the enclosing function as a "
            f"factory contract in {CENTRAL})", node.lineno)

    # -- call sites of bound jit callables ----------------------------

    def _check_boundary_call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        if d is None:
            return
        cname = self.bound_names.get(d.rsplit(".", 1)[-1])
        if cname is None:
            return
        contract = self.contracts[cname]
        qual = self._qual()
        for kw in node.keywords:
            if kw.arg in contract["static_argnames"] and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set)):
                self._emit(
                    "unhashable-static-arg", qual, f"{d}:{kw.arg}",
                    f"static arg {kw.arg!r} of {cname!r} is an "
                    f"unhashable {type(kw.value).__name__.lower()} "
                    f"literal (TypeError at trace time)", kw.value.lineno)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if self._len_shaped(arg):
                self._emit(
                    "value-dependent-shape", qual, d,
                    f"argument of registered jit {cname!r} is built "
                    f"inline with a len()-derived shape — route it "
                    f"through the staging size classes / pow2 buckets "
                    f"so the compiled-program count stays bounded",
                    arg.lineno)

    @staticmethod
    def _len_shaped(arg: ast.AST) -> bool:
        if not (isinstance(arg, ast.Call) and dotted(arg.func)):
            return False
        terminal = dotted(arg.func).rsplit(".", 1)[-1]
        if terminal not in _CREATION_FNS:
            return False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and dotted(sub.func) == "len":
                return True
        return False


class JitStabilityPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        contracts = declared_contracts(project.root)
        findings: List[Finding] = []
        # Pre-seed the callable-name → contract map from the declared
        # SITES so call-site checks (unhashable statics, len-shapes)
        # work regardless of file visit order; tracked bindings
        # discovered during the sweep extend it for fixture-local and
        # assignment-form jits (same-file call sites only, by design —
        # cross-file callables are expected to be contract sites).
        bound: Dict[str, str] = {}
        for name, c in contracts.items():
            qual = c["site"].split("::", 1)[-1]
            if qual:
                bound.setdefault(qual.rsplit(".", 1)[-1], name)
        for src in project.files:
            _SiteVisitor(src, contracts, findings, bound).visit(src.tree)
        return findings
