"""Shared wire-contract derivation for the protocol passes.

Pure-AST views of the wire registry (spacedrive_tpu/p2p/wire.py) the
three protocol passes cross-check — same no-package-import constraint
as `_sql` / crdt_parity: the linted tree is never imported.

- `decls_in_tree` parses `declare_message(...)` calls — from the
  central registry and from any project file (fixtures declare their
  own bad/ok cases; `project_decls` lets fixture declarations win on
  name collision so cases stay self-contained). Only literal
  arguments participate; a computed declaration is invisible to the
  static side and is reported by wire-discipline's
  computed-declaration code.
- `MsgDecl.consts` is the t/kind discriminator surface
  (raw-kind-literal hunts hand-built frames by it), `.fields` the
  schema token map schema-drift validates reads/packs against.
- `proto_versions` parses the PROTO_VERSIONS literal — the version
  the proto-compat snapshot diff keys bumps on.
- `snapshot_entry` renders one declaration the way
  `wire.baseline_snapshot()` does, so the committed
  tools/sdlint/wire_baseline.json and the AST view diff key-for-key.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core import Project, dotted

WIRE_PATH = "spacedrive_tpu/p2p/wire.py"
BASELINE_PATH = "tools/sdlint/wire_baseline.json"
SCOPE_MARKER = "# sdlint-scope: wire"
# The wire plane's product scope: the modules that speak frames.
SCOPE_PREFIXES = ("spacedrive_tpu/p2p/", "spacedrive_tpu/sync/")

PACK_APIS = ("pack", "unpack")
REGISTRY_READS = ("proto", "slice_cap", "message")


def in_scope(src) -> bool:
    """Wire-plane scope: p2p/ + sync/ product modules, plus any file
    opting in with the `# sdlint-scope: wire` marker (fixtures)."""
    if src.relpath == WIRE_PATH:
        return False
    if src.relpath.startswith(SCOPE_PREFIXES):
        return True
    return SCOPE_MARKER in "\n".join(src.lines[:5])


def _fold_int(node: ast.AST) -> Optional[int]:
    """Constant-fold the int expressions declarations use
    (`64 * 1024 * 1024`, `4096`, `48 << 20`)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.BinOp):
        left = _fold_int(node.left)
        right = _fold_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.LShift):
            return left << right
    return None


@dataclass(frozen=True)
class MsgDecl:
    name: str
    proto: str
    direction: str
    # field name -> schema token ("str", "int?", "=ping", "=proto?");
    # None for values/binary messages.
    fields: Optional[Dict[str, str]]
    values: Optional[Tuple[str, ...]]
    binary: bool
    size_cap: Optional[int]          # None = computed (invisible)
    slice_cap: Optional[int]
    timeout_budget: str
    path: str
    lineno: int

    @property
    def consts(self) -> Dict[str, str]:
        """The t/kind discriminator literals this message is
        dispatched on (`=proto` version consts excluded)."""
        out: Dict[str, str] = {}
        for f, tok in (self.fields or {}).items():
            if f in ("t", "kind") and tok.startswith("=") \
                    and tok not in ("=proto", "=proto?"):
                out[f] = tok[1:]
        return out

    def required(self) -> List[str]:
        """Field names pack() cannot fill itself: non-const,
        non-optional."""
        out = []
        for f, tok in (self.fields or {}).items():
            if not tok.startswith("=") and not tok.endswith("?"):
                out.append(f)
        return out


def decls_in_tree(tree: ast.AST, relpath: str) -> List[MsgDecl]:
    out: List[MsgDecl] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None or d.split(".")[-1] != "declare_message":
            continue
        args = list(node.args)
        kw = {k.arg: k.value for k in node.keywords if k.arg}

        def _str(n) -> Optional[str]:
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                return n.value
            return None

        name = _str(args[0]) if args else None
        proto = _str(args[1]) if len(args) > 1 else None
        direction = _str(args[2]) if len(args) > 2 else None
        if name is None or proto is None or direction is None:
            continue  # computed declaration — invisible statically
        schema_node = args[3] if len(args) > 3 else kw.get("schema")
        fields: Optional[Dict[str, str]] = None
        if isinstance(schema_node, ast.Dict):
            fields = {}
            for k, v in zip(schema_node.keys, schema_node.values):
                fk, fv = _str(k), _str(v)
                if fk is None or fv is None:
                    fields = None
                    break
                fields[fk] = fv
        values: Optional[Tuple[str, ...]] = None
        vnode = kw.get("values")
        if isinstance(vnode, ast.Tuple):
            vals = [_str(e) for e in vnode.elts]
            if all(v is not None for v in vals):
                values = tuple(vals)  # type: ignore[arg-type]
        binary = bool(isinstance(kw.get("binary"), ast.Constant)
                      and kw["binary"].value)
        size_cap = _fold_int(kw["size_cap"]) if "size_cap" in kw else None
        slice_cap = _fold_int(kw["slice_cap"]) \
            if "slice_cap" in kw else None
        budget = _str(kw.get("timeout_budget")) or ""
        out.append(MsgDecl(name, proto, direction, fields, values,
                           binary, size_cap, slice_cap, budget,
                           relpath, node.lineno))
    return out


def _registry_tree(root: str) -> Optional[ast.AST]:
    path = os.path.join(root, WIRE_PATH)
    try:
        return ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return None


def registry_decls(root: str) -> Dict[str, MsgDecl]:
    tree = _registry_tree(root)
    if tree is None:
        return {}
    return {d.name: d for d in decls_in_tree(tree, WIRE_PATH)}


def project_decls(project: Project) -> Dict[str, MsgDecl]:
    """Central registry + declarations inside the linted files
    (fixtures). Project files win on name collision so fixture cases
    stay self-contained."""
    decls = registry_decls(project.root)
    for src in project.files:
        if src.relpath == WIRE_PATH:
            continue
        for d in decls_in_tree(src.tree, src.relpath):
            decls[d.name] = d
    return decls


def proto_versions(root: str) -> Dict[str, int]:
    """The PROTO_VERSIONS literal from the central registry."""
    tree = _registry_tree(root)
    if tree is None:
        return {}
    for node in ast.walk(tree):
        # the registry annotates it (PROTO_VERSIONS: Dict[str, int])
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = node.targets
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "PROTO_VERSIONS"
                   for t in targets):
            continue
        if isinstance(node.value, ast.Dict):
            out: Dict[str, int] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(v, ast.Constant):
                    out[str(k.value)] = int(v.value)
            return out
    return {}


def const_index(decls: Dict[str, MsgDecl]) -> Dict[str, str]:
    """Discriminator literal -> message name ('t=ping' / 'kind=ack'
    keys so raw-kind-literal can point at the declaration)."""
    out: Dict[str, str] = {}
    for name, d in decls.items():
        for f, v in d.consts.items():
            out[f"{f}={v}"] = name
    return out


def value_index(decls: Dict[str, MsgDecl]) -> Dict[str, str]:
    """Bare-string values ('ok', 'accept', ...) -> message name."""
    out: Dict[str, str] = {}
    for name, d in decls.items():
        for v in d.values or ():
            out[v] = name
    return out


def snapshot_entry(d: MsgDecl, versions: Dict[str, int]) -> dict:
    """One declaration rendered the way wire.baseline_snapshot() does
    — the unit the proto-compat diff compares."""
    entry: dict = {
        "proto": d.proto,
        "version": versions.get(d.proto, 0),
        "size_cap": d.size_cap,
    }
    if d.fields is not None:
        entry["schema"] = dict(sorted(d.fields.items()))
    elif d.values is not None:
        entry["values"] = list(d.values)
    else:
        entry["binary"] = True
    if d.slice_cap is not None:
        entry["slice_cap"] = d.slice_cap
    return entry


def imports_wire(tree: ast.AST) -> Dict[str, str]:
    """Names bound from the wire module in this file: alias -> api
    name ('' for the module itself). Covers `from . import wire`,
    `from ..p2p import wire`, `from .wire import pack, unpack`,
    `import spacedrive_tpu.p2p.wire as wire`."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = (node.module or "").rsplit(".", 1)[-1]
            for a in node.names:
                if a.name == "wire":
                    out[a.asname or a.name] = ""
                elif mod == "wire":
                    out[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.rsplit(".", 1)[-1] == "wire":
                    out[(a.asname or a.name).split(".")[0]] = ""
    return out


def wire_call(site_name: str, bound: Dict[str, str]) -> Optional[str]:
    """The wire API a dotted call resolves to ('pack', 'unpack',
    'proto', 'slice_cap', ...), or None if it is not a wire call."""
    parts = site_name.split(".")
    if len(parts) == 2 and bound.get(parts[0]) == "":
        return parts[1]
    if len(parts) == 1 and bound.get(parts[0], None):
        return bound[parts[0]]
    return None
