"""Shared SQL-contract derivation for the store passes.

Pure-AST views of the two registries the store passes cross-check
(same no-package-import constraint as crdt_parity / the timeout
cross-check in backpressure):

- `collect_decls` parses `declare_stmt` / `declare_shape` calls —
  from the central registry (spacedrive_tpu/store/statements.py) and
  from any project file (fixtures declare their own bad/ok cases).
  Only literal arguments participate; a computed declaration is
  invisible to the static side and is reported by sql-discipline's
  central-registry code.
- `models_schema` parses store/models.py into tables → columns plus
  the index surface (pk / unique / index / lazy_index first columns)
  schema-parity validates statements against.
- `ShapeIndex` compiles declared shape skeletons into matchers for
  BOTH sides of the contract: the runtime auditor matches rendered
  SQL; here the static side matches f-string call sites whose
  FormattedValue slots are replaced by a sentinel identifier.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import SourceFile, dotted

STATEMENTS_PATH = "spacedrive_tpu/store/statements.py"
MODELS_PATH = "spacedrive_tpu/store/models.py"

# Mirrors statements.py (the runtime registry validates the same way;
# the drift test in tests/test_sdlint.py pins the two sets equal).
DML_HEADS = ("SELECT", "INSERT", "UPDATE", "DELETE", "REPLACE", "WITH")
WRITE_HEADS = ("INSERT", "UPDATE", "DELETE", "REPLACE")

_WS_RE = re.compile(r"\s+")
_IDENT = r"[A-Za-z_][A-Za-z0-9_]*"
# The sentinel a call-site f-string slot renders to for matching.
DYN = "sdlint_dyn"


def normalize_sql(sql: str) -> str:
    return _WS_RE.sub(" ", sql).strip().rstrip(";").strip()


def sql_head(sql: str) -> str:
    s = normalize_sql(sql)
    return s.split(" ", 1)[0].upper() if s else ""


@dataclass(frozen=True)
class Decl:
    name: str
    sql: str                 # normalized; skeleton text for shapes
    verb: str
    tables: Tuple[str, ...]
    tx_required: bool
    cardinality: str
    coverage: str
    shape: bool
    path: str
    lineno: int


def _const(node) -> Optional[object]:
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Tuple) and all(
            isinstance(e, ast.Constant) for e in node.elts):
        return tuple(e.value for e in node.elts)
    return None


def decls_in_tree(tree: ast.AST, relpath: str) -> List[Decl]:
    out: List[Decl] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        last = d.split(".")[-1]
        if last not in ("declare_stmt", "declare_shape"):
            continue
        args = list(node.args)
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        name = _const(args[0]) if args else None
        sql = _const(args[1]) if len(args) > 1 else None
        if not isinstance(name, str) or not isinstance(sql, str):
            continue  # computed declaration — invisible statically
        verb = _const(kw.get("verb")) or ""
        tables = _const(kw.get("tables")) or ()
        if isinstance(tables, str):
            tables = (tables,)
        tx = bool(_const(kw.get("tx_required")) or False)
        card = _const(kw.get("cardinality"))
        if not isinstance(card, str):
            card = "none" if verb != "read" else ""
        coverage = _const(kw.get("coverage")) or "tier1"
        out.append(Decl(
            name, normalize_sql(sql), str(verb), tuple(tables), tx,
            str(card), str(coverage), last == "declare_shape",
            relpath, node.lineno))
    return out


def registry_decls(root: str) -> Dict[str, Decl]:
    """Declarations from the central registry file (by AST)."""
    path = os.path.join(root, STATEMENTS_PATH)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return {}
    return {d.name: d for d in decls_in_tree(tree, STATEMENTS_PATH)}


def project_decls(project) -> Dict[str, Decl]:
    """Central registry + declarations inside the linted files
    (fixtures). Project files win on name collision so fixture cases
    stay self-contained."""
    decls = registry_decls(project.root)
    for src in project.files:
        if src.relpath == STATEMENTS_PATH:
            continue
        for d in decls_in_tree(src.tree, src.relpath):
            decls[d.name] = d
    return decls


# -- shape matching ---------------------------------------------------------

class ShapeIndex:
    """Compiled shape skeletons. `{i}`/`{w}` slots become regex groups;
    the static side matches call-site skeletons whose dynamic slots
    render as the DYN sentinel (registry membership of `{i}` captures
    is the runtime auditor's job — statically the identifier is
    unknown)."""

    def __init__(self, decls: Dict[str, Decl]):
        self.patterns: List[Tuple[re.Pattern, Decl]] = []
        for d in decls.values():
            if not d.shape:
                continue
            parts = []
            for tok in re.split(r"(\{i\}|\{w\})", d.sql):
                if tok == "{i}":
                    parts.append(f"(?:{_IDENT})")
                elif tok == "{w}":
                    parts.append(r"(?:.*?)")
                else:
                    parts.append(re.escape(tok))
            self.patterns.append(
                (re.compile("^" + "".join(parts) + "$", re.DOTALL), d))

    def match(self, rendered: str) -> Optional[Decl]:
        rendered = normalize_sql(rendered)
        for pat, d in self.patterns:
            if pat.match(rendered):
                return d
        return None


def render_fstring(node: ast.JoinedStr) -> str:
    """An f-string with every dynamic slot replaced by the sentinel
    identifier — the static half of shape matching."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append(DYN)
    return "".join(parts)


def literal_sql(node: ast.AST) -> Optional[str]:
    """String constant (incl. implicit concatenation) that LOOKS like
    DML SQL, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if sql_head(node.value) in DML_HEADS:
            return node.value
    return None


def dynamic_sql_expr(node: ast.AST) -> Optional[str]:
    """Rendered sentinel text when `node` is a dynamically-BUILT SQL
    string (f-string, %-format, .format, +-concatenation) whose
    constant prefix looks like DML; else None."""
    if isinstance(node, ast.JoinedStr):
        rendered = render_fstring(node)
        if sql_head(rendered) in DML_HEADS:
            return rendered
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Mod)):
        left = node.left
        while isinstance(left, ast.BinOp):
            left = left.left
        base = None
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            base = left.value
        elif isinstance(left, ast.JoinedStr):
            base = render_fstring(left)
        if base is not None and sql_head(base) in DML_HEADS:
            return _render_concat(node)
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d is not None and d.split(".")[-1] == "format":
            recv = node.func
            if isinstance(recv, ast.Attribute) and isinstance(
                    recv.value, ast.Constant) and isinstance(
                    recv.value.value, str):
                if sql_head(recv.value.value) in DML_HEADS:
                    return re.sub(r"\{[^}]*\}", DYN, recv.value.value)
    return None


def _render_concat(node: ast.AST) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return render_fstring(node)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _render_concat(node.left) + _render_concat(node.right)
    return DYN


# -- models schema ----------------------------------------------------------

@dataclass
class ModelsInfo:
    columns: Dict[str, Set[str]] = field(default_factory=dict)
    # per table: columns that can answer an indexed lookup (pk, unique
    # field, first column of a unique/index/lazy_index tuple)
    indexed: Dict[str, Set[str]] = field(default_factory=dict)


def models_schema(root: str) -> ModelsInfo:
    info = ModelsInfo()
    path = os.path.join(root, MODELS_PATH)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return info
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "register"):
            continue
        for arg in node.args:
            if not (isinstance(arg, ast.Call)
                    and dotted(arg.func) == "Model"):
                continue
            name = None
            if arg.args and isinstance(arg.args[0], ast.Constant):
                name = arg.args[0].value
            if not isinstance(name, str):
                continue
            cols: Set[str] = set()
            idx: Set[str] = set()
            fields_node = arg.args[1] if len(arg.args) > 1 else None
            if isinstance(fields_node, ast.Tuple):
                for f in fields_node.elts:
                    if isinstance(f, ast.Call):
                        fd = dotted(f.func)
                        if fd == "Field" and f.args and isinstance(
                                f.args[0], ast.Constant):
                            cname = f.args[0].value
                            cols.add(cname)
                            for k in f.keywords:
                                if k.arg in ("primary_key", "unique") \
                                        and isinstance(k.value,
                                                       ast.Constant) \
                                        and k.value.value:
                                    idx.add(cname)
                        elif fd == "_id":
                            cols.add("id")
                            idx.add("id")
                        elif fd == "_pub_id":
                            cols.add("pub_id")
                            idx.add("pub_id")
            for k in arg.keywords:
                if k.arg in ("uniques", "indexes", "lazy_indexes") \
                        and isinstance(k.value, ast.Tuple):
                    for tup in k.value.elts:
                        if isinstance(tup, ast.Tuple) and tup.elts \
                                and isinstance(tup.elts[0],
                                               ast.Constant):
                            idx.add(tup.elts[0].value)
            info.columns[name] = cols
            info.indexed[name] = idx
    return info


# -- lightweight SQL introspection ------------------------------------------

_STRING_RE = re.compile(r"'(?:[^']|'')*'")
_TABLE_RE = re.compile(
    r"\b(?:FROM|JOIN|INTO|UPDATE)\s+(" + _IDENT + r")", re.IGNORECASE)
_QUAL_RE = re.compile(r"\b(" + _IDENT + r")\.(" + _IDENT + r"|\*)")
_AS_RE = re.compile(r"\bAS\s+(" + _IDENT + r")", re.IGNORECASE)
_ALIAS_RE = re.compile(
    r"\b(?:FROM|JOIN)\s+(" + _IDENT + r")\s+(?:AS\s+)?(" + _IDENT + r")",
    re.IGNORECASE)
_IDENT_RE = re.compile(r"\b(" + _IDENT + r")\b")

# Keywords + SQLite functions that appear in this inventory's SQL.
SQL_WORDS = frozenset(w.upper() for w in """
select from where and or not in as join left right inner outer on
group by order limit offset insert into values update set delete
replace distinct having asc desc like escape is null between exists
case when then else end union all conflict do nothing excluded
count max min sum avg lower upper replace coalesce length abs
last_insert_rowid strftime glob primary key
ignore abort fail rollback savepoint release begin immediate
""".split())


def strip_strings(sql: str) -> str:
    return _STRING_RE.sub("''", sql)


def parse_tables(sql: str) -> Set[str]:
    s = strip_strings(normalize_sql(sql))
    return {m.group(1) for m in _TABLE_RE.finditer(s)
            if m.group(1) != DYN and m.group(1).upper() not in SQL_WORDS}


def parse_identifiers(sql: str) -> Tuple[Set[str], Dict[str, str],
                                         Set[str]]:
    """(bare identifier tokens, alias→table map, result aliases) of a
    statement — everything schema-parity needs to resolve columns."""
    s = strip_strings(normalize_sql(sql))
    aliases: Dict[str, str] = {}
    for m in _ALIAS_RE.finditer(s):
        tbl, al = m.group(1), m.group(2)
        if al.upper() not in SQL_WORDS and tbl.upper() not in SQL_WORDS:
            aliases[al] = tbl
    result_aliases = {m.group(1) for m in _AS_RE.finditer(s)}
    # Qualified refs (alias.col) are checked separately — strip them
    # so neither half leaks into the bare-identifier sweep.
    bare_src = _QUAL_RE.sub(" ", s)
    idents = {m.group(1) for m in _IDENT_RE.finditer(bare_src)
              if m.group(1).upper() not in SQL_WORDS}
    return idents, aliases, result_aliases


def parse_qualified(sql: str) -> List[Tuple[str, str]]:
    s = strip_strings(normalize_sql(sql))
    return [(m.group(1), m.group(2)) for m in _QUAL_RE.finditer(s)]


def where_columns(sql: str) -> Set[str]:
    """Identifier tokens inside WHERE/ON clauses (filter surface)."""
    s = strip_strings(normalize_sql(sql))
    out: Set[str] = set()
    for m in re.finditer(
            r"\b(?:WHERE|ON)\b(.*?)(?=\bGROUP\b|\bORDER\b|\bLIMIT\b|$)",
            s, re.IGNORECASE | re.DOTALL):
        clause = m.group(1)
        for t in _IDENT_RE.finditer(clause):
            tok = t.group(1)
            if tok.upper() not in SQL_WORDS and tok != DYN:
                out.add(tok)
    return out
