"""Pass: schema-drift — field traffic matches the declared schema.

The cross-AST half of the wire contracts (the `_sql`-style check PR 12
ran between call sites and statements.py, applied to frames): the
registry (spacedrive_tpu/p2p/wire.py) declares each message's field
tokens, and this pass holds the OTHER side of every exchange to them —
what a sender packs, and what a receiver reads off an unpacked frame.
The runtime auditor catches live drift; this catches it at lint time,
including the field nobody ever sends (a read of a key no declaration
carries is dead code at best, a silently-None `get` at worst).

Scope: same wire-plane scope as wire-discipline (`spacedrive_tpu/p2p/`
+ `spacedrive_tpu/sync/` + `# sdlint-scope: wire` marker files).

Codes:

- ``unknown-field-read``: `x["f"]` / `x.get("f")` where `x` was
  assigned from `wire.unpack("name", ...)` in the same function and
  `f` is not in the declared schema — the declaration says no peer
  ever sends it.
- ``missing-field``: a `wire.pack("name", ...)` call with literal
  kwargs that omits a declared required field (non-const,
  non-optional) — the call raises WireSchemaError at runtime;
  `**kwargs` packs are skipped (statically unknowable).
- ``smuggled-field``: a pack kwarg (or a hand-built discriminator
  frame's key) absent from the declared schema — undeclared fields
  must be declared, not smuggled past the contract.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, dotted, own_body_walk
from . import _wire

PASS = "schema-drift"


class SchemaDriftPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        decls = _wire.project_decls(project)
        consts = _wire.const_index(decls)
        findings: List[Finding] = []
        for fn in project.index.funcs:
            src = fn.src
            if not _wire.in_scope(src):
                continue
            bound = _wire.imports_wire(src.tree)
            self._check_packs(fn, bound, decls, findings)
            self._check_reads(fn, bound, decls, findings)
        for src in project.files:
            if not _wire.in_scope(src):
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Dict):
                    self._check_literal_frame(
                        src, node, decls, consts, findings)
        return findings

    # -- sender side --------------------------------------------------------

    def _check_packs(self, fn, bound, decls, findings) -> None:
        for site in fn.calls:
            if _wire.wire_call(site.name, bound) != "pack":
                continue
            call = site.node
            first = call.args[0] if call.args else None
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic-kind (wire-discipline's finding)
            d = decls.get(first.value)
            if d is None or d.fields is None:
                continue
            if any(k.arg is None for k in call.keywords):
                continue  # **kwargs — statically unknowable
            given = {k.arg for k in call.keywords}
            for f in given:
                if f not in d.fields:
                    findings.append(Finding(
                        PASS, "smuggled-field", fn.src.relpath,
                        fn.qual, f"{d.name}.{f}",
                        f"pack({d.name!r}) passes field {f!r} absent "
                        "from the declared schema — declare it, do "
                        "not smuggle it",
                        call.lineno))
            for f in d.required():
                if f not in given:
                    findings.append(Finding(
                        PASS, "missing-field", fn.src.relpath,
                        fn.qual, f"{d.name}.{f}",
                        f"pack({d.name!r}) omits required field "
                        f"{f!r} (declared "
                        f"{d.fields.get(f, '?')!r}) — the call "
                        "raises WireSchemaError at runtime",
                        call.lineno))

    # -- receiver side ------------------------------------------------------

    def _check_reads(self, fn, bound, decls, findings) -> None:
        # name -> [(assign lineno, MsgDecl | None)]: unpack assigns
        # carry their declaration; ANY other assign clears tracking
        # from its line on (the var no longer holds an unpacked
        # frame). A read resolves to the latest assign at or above
        # its line.
        assigns: Dict[str, List[Tuple[int, Optional[object]]]] = {}
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if not targets:
                continue
            d = None
            if isinstance(node.value, ast.Call):
                cd = dotted(node.value.func)
                if cd is not None and \
                        _wire.wire_call(cd, bound) == "unpack":
                    first = node.value.args[0] \
                        if node.value.args else None
                    if isinstance(first, ast.Constant) and \
                            isinstance(first.value, str):
                        d = decls.get(first.value)
            for t in targets:
                assigns.setdefault(t, []).append((node.lineno, d))
        if not assigns:
            return

        def decl_at(var: str, lineno: int):
            # highest assign line at-or-above the read (the walk does
            # not yield in source order)
            best_ln, best = -1, None
            for ln, d in assigns.get(var, ()):
                if best_ln < ln <= lineno:
                    best_ln, best = ln, d
            return best

        for node in own_body_walk(fn.node):
            var = field = None
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                var, field = node.value.id, node.slice.value
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                var, field = node.func.value.id, node.args[0].value
            if var is None:
                continue
            d = decl_at(var, node.lineno)
            if d is None or d.fields is None:
                continue
            if field not in d.fields:
                findings.append(Finding(
                    PASS, "unknown-field-read", fn.src.relpath,
                    fn.qual, f"{d.name}.{field}",
                    f"reads field {field!r} off a frame unpacked as "
                    f"{d.name!r}, whose declaration has no such "
                    "field — no peer ever sends it",
                    node.lineno))

    # -- hand-built frames --------------------------------------------------

    def _check_literal_frame(self, src, node: ast.Dict, decls,
                             consts, findings) -> None:
        name = None
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value in ("t", "kind") \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                name = consts.get(f"{k.value}={v.value}")
        if name is None:
            return
        d = decls[name]
        if d.fields is None:
            return
        keys = [k.value for k in node.keys
                if isinstance(k, ast.Constant)]
        if len(keys) != len(node.keys):
            return  # **splat — statically unknowable
        for f in keys:
            if f not in d.fields:
                findings.append(Finding(
                    PASS, "smuggled-field", src.relpath, "",
                    f"{name}.{f}",
                    f"hand-built {name!r} frame carries field {f!r} "
                    "absent from the declared schema",
                    node.lineno))
        for f in d.required():
            if f not in keys:
                findings.append(Finding(
                    PASS, "missing-field", src.relpath, "",
                    f"{name}.{f}",
                    f"hand-built {name!r} frame omits required "
                    f"field {f!r} — the receiver's unpack refuses it",
                    node.lineno))
