"""Pass: proto-compat — schema changes bump versions, decodes stay caged.

A wire contract is a promise to OTHER nodes: changing a message's
shape without bumping its proto group's version ships two
incompatible decoders under one version number — the silent-corruption
failure SYNC_PROTO's comment describes. This pass diffs the current
registry (spacedrive_tpu/p2p/wire.py, by AST) against the COMMITTED
snapshot `tools/sdlint/wire_baseline.json` (regenerated via
`python -m tools.sdlint --write-wire-baseline` — reviewing that diff
is reviewing the compat story), and polices the decode paths that
would bypass the registry's caging.

Fixtures embed their own expected snapshot as a module-level
``WIRE_BASELINE = {...}`` dict literal (fixture entries win), so
cases stay self-contained.

Codes:

- ``schema-no-bump``: a message's schema/values/size_cap changed
  from the snapshot while its proto group's version did not — bump
  the version in wire.PROTO_VERSIONS (both refusal directions key on
  it) and regenerate the snapshot.
- ``missing-snapshot``: a declared message absent from the snapshot
  — regenerate it so the NEXT change has a baseline to diff against.
- ``removed-message``: a snapshot message no longer declared —
  removal is a compat event too (old peers still send it); bump and
  regenerate.
- ``adhoc-version-check``: comparing a frame's raw ``proto`` field
  in wire-plane code — `wire.unpack` IS the version check (it
  raises WireVersionError on skew); a hand-rolled compare drifts
  from the registry's version the moment it bumps.
- ``raw-decode``: `msgpack.unpackb` in `spacedrive_tpu/p2p/` outside
  wire.py/proto.py — frames must enter through the tunnel seam
  (read_msg/recv), where the size cap and the armed auditor live.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List

from ..core import Finding, Project
from . import _wire

PASS = "proto-compat"

# proto.py holds the transport's own decode (read_msg / Tunnel.recv —
# the audit seam itself); everything else in p2p/ must not re-decode.
DECODE_EXEMPT = (_wire.WIRE_PATH, "spacedrive_tpu/p2p/proto.py")


def committed_baseline(root: str) -> Dict[str, dict]:
    path = os.path.join(root, _wire.BASELINE_PATH)
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data.get("messages", {})


def fixture_baselines(project: Project) -> Dict[str, dict]:
    """Module-level ``WIRE_BASELINE = {...}`` literals in linted
    files — the fixture-wins half of the snapshot."""
    out: Dict[str, dict] = {}
    for src in project.files:
        for node in src.tree.body:
            if not (isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "WIRE_BASELINE"
                    for t in node.targets)):
                continue
            try:
                val = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(val, dict):
                for k, v in val.items():
                    if isinstance(k, str) and isinstance(v, dict):
                        out[k] = v
    return out


class ProtoCompatPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        decls = _wire.project_decls(project)
        versions = _wire.proto_versions(project.root)
        baseline = dict(committed_baseline(project.root))
        baseline.update(fixture_baselines(project))

        decl_paths = {d.path: d.lineno for d in decls.values()}
        anchor_path = _wire.WIRE_PATH

        for name, d in sorted(decls.items()):
            entry = baseline.get(name)
            if entry is None:
                findings.append(Finding(
                    PASS, "missing-snapshot", d.path, "", name,
                    f"wire message {name!r} has no entry in "
                    f"{_wire.BASELINE_PATH} — regenerate it "
                    "(python -m tools.sdlint --write-wire-baseline) "
                    "so the next change diffs against a baseline",
                    d.lineno))
                continue
            cur = _wire.snapshot_entry(d, versions)
            shape_changed = any(
                cur.get(k) != entry.get(k)
                for k in ("schema", "values", "binary", "size_cap",
                          "slice_cap"))
            if shape_changed and cur.get("version") == \
                    entry.get("version"):
                findings.append(Finding(
                    PASS, "schema-no-bump", d.path, "", name,
                    f"wire message {name!r} changed shape against "
                    f"{_wire.BASELINE_PATH} but proto group "
                    f"{d.proto!r} is still version "
                    f"{cur.get('version')} — two incompatible "
                    "decoders under one version number; bump "
                    "PROTO_VERSIONS and regenerate the snapshot",
                    d.lineno))
        for name, entry in sorted(baseline.items()):
            if name not in decls:
                findings.append(Finding(
                    PASS, "removed-message", anchor_path, "", name,
                    f"snapshot message {name!r} is no longer "
                    "declared — old peers still send it; removal is "
                    "a compat event (bump + regenerate)",
                    decl_paths.get(anchor_path, 1)))

        for src in project.files:
            in_scope = _wire.in_scope(src)
            for node in ast.walk(src.tree):
                if in_scope and isinstance(node, ast.Compare):
                    self._check_version_compare(src, node, findings)
                if isinstance(node, ast.Call) and \
                        self._is_unpackb(node) and \
                        src.relpath.startswith("spacedrive_tpu/p2p/") \
                        and src.relpath not in DECODE_EXEMPT:
                    findings.append(Finding(
                        PASS, "raw-decode", src.relpath, "",
                        "msgpack.unpackb",
                        "raw msgpack.unpackb in the p2p plane: "
                        "frames enter through the tunnel seam "
                        "(read_msg/recv), where the size cap and "
                        "the armed frame auditor live",
                        node.lineno))
        return findings

    @staticmethod
    def _is_unpackb(node: ast.Call) -> bool:
        f = node.func
        return isinstance(f, ast.Attribute) and f.attr == "unpackb"

    def _check_version_compare(self, src, node: ast.Compare,
                               findings: List[Finding]) -> None:
        for side in (node.left, *node.comparators):
            field = None
            if isinstance(side, ast.Subscript) and \
                    isinstance(side.slice, ast.Constant):
                field = side.slice.value
            elif isinstance(side, ast.Call) and \
                    isinstance(side.func, ast.Attribute) and \
                    side.func.attr == "get" and side.args and \
                    isinstance(side.args[0], ast.Constant):
                field = side.args[0].value
            if field == "proto":
                findings.append(Finding(
                    PASS, "adhoc-version-check", src.relpath, "",
                    "proto-compare",
                    "hand-rolled proto-field compare: wire.unpack "
                    "IS the version check (WireVersionError on "
                    "skew) — a local compare drifts from the "
                    "registry the moment PROTO_VERSIONS bumps",
                    node.lineno))
                return
