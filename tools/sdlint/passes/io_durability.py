"""Pass: io-durability — every durable write goes through persist.py.

A crash between `write()` and `close()` (or between `close()` and the
directory catching up) turns "saved" into a torn file with a valid
name — the exact failure the round-18 incident bundles kept
attributing to "disk full" because nothing else could name it. The
discipline mirrors the PR 12 timeout registry: every durable on-disk
artifact is DECLARED by name in `spacedrive_tpu/persist.py`
(path pattern, kind, fsync policy, recovery note — README table
generated from the registry) and written by name through
`persist.atomic_write` / `persist.wal_writer` / `persist.seal` /
`persist.scratch` / `persist.db_write`.

Scope: product modules under `spacedrive_tpu/` for the write-shape
rules (tools/ write BENCH artifacts through the same seam, but their
stdout/report plumbing is not durable state); artifact-NAME rules
apply to every persist call site in the whole lint scope.

Codes:

- ``bare-write``: builtin `open()` for write/append/create (or a
  `+` update mode) in product code — a bare file write has no tmp,
  no fsync and no recovery story; route it through the persist seam
  or waive it with the reason the bytes are not durable state
  (streaming user output, caller-owned target, in-place destruction).
- ``rename-no-tmp``: `os.rename`/`os.replace` whose SOURCE carries no
  tmp/part token — renaming a non-scratch name is not a commit
  protocol, it is two racing names for the same bytes. User-file
  moves (the fs-ops jobs) waive with that reason.
- ``replace-no-fsync``: raw `os.replace` in product code with no
  `fsync` anywhere in the same function: the classic
  write→rename-without-flush, durable in name only. The persist seam
  orders fsync-file → rename → fsync-dir per declared policy.
- ``artifact-undeclared``: a persist call names an artifact missing
  from the `declare_artifact(...)` registry.
- ``artifact-dynamic``: a persist call with a non-literal name — the
  artifact table (and the crash grid built from it) must be static.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set

from ..core import Finding, Project, dotted, own_body_walk

PASS = "io-durability"

CENTRAL = "spacedrive_tpu/persist.py"
PRODUCT_PREFIX = "spacedrive_tpu/"
SCOPE_MARKER = "# sdlint-scope: persist"

# persist entry points whose first argument is a declared artifact
# name (the registry key the static table and the crash grid share).
NAMED_APIS = {"atomic_write", "wal_writer", "scratch", "seal",
              "db_write", "recover", "crashpoint", "edges_for",
              "artifact"}

_WRITE_MODE_CHARS = set("wax+")
_TMP_TOKENS = ("tmp", "part", "bak", "swap", "stage")


def declared_artifacts(root: str) -> Dict[str, str]:
    """name -> kind from `declare_artifact(...)` calls in the central
    registry (AST — the linted tree is never imported)."""
    out: Dict[str, str] = {}
    path = os.path.join(root, CENTRAL)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "declare_artifact"
                and node.args):
            continue
        name = node.args[0]
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            kind = ""
            if len(node.args) > 2 and \
                    isinstance(node.args[2], ast.Constant):
                kind = str(node.args[2].value)
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    kind = str(kw.value.value)
            out[name.value] = kind
    return out


def _open_write_mode(call: ast.Call) -> str:
    """The literal mode of a builtin `open()` call iff it writes."""
    if dotted(call.func) != "open":
        return ""
    mode = None
    if len(call.args) > 1:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return ""
    if _WRITE_MODE_CHARS & set(mode.value):
        return mode.value
    return ""


def _has_tmp_token(node: ast.AST) -> bool:
    """Any tmp/part-ish token in the expression: a variable named
    `tmp_path`, a `".part"` literal in a concat, an f-string piece."""
    for sub in ast.walk(node):
        text = ""
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            text = sub.value
        elif isinstance(sub, ast.Name):
            text = sub.id
        elif isinstance(sub, ast.Attribute):
            text = sub.attr
        if text and any(t in text.lower() for t in _TMP_TOKENS):
            return True
    return False


class IoDurabilityPass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_artifacts(project.root)
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for fn in project.index.funcs:
            rel = fn.src.relpath
            if rel == CENTRAL:
                continue  # the seam's own tmp-write IS the protocol
            head = "\n".join(fn.src.lines[:5])
            product = rel.startswith(PRODUCT_PREFIX) or \
                SCOPE_MARKER in head
            has_fsync = any(
                site.name.rsplit(".", 1)[-1] == "fsync"
                for site in fn.calls)
            for site in fn.calls:
                call, d = site.node, site.name
                last = d.rsplit(".", 1)[-1]
                if product:
                    mode = _open_write_mode(call)
                    if mode:
                        emit(Finding(
                            PASS, "bare-write", rel, fn.qual,
                            f"open:{mode}",
                            f"bare open(..., {mode!r}) in product "
                            "code: no tmp, no fsync, no recovery "
                            "story — write through the persist seam "
                            "(persist.atomic_write / wal_writer / "
                            "seal) or waive with the reason these "
                            "bytes are not durable state",
                            call.lineno))
                    if d in ("os.rename", "os.replace"):
                        src_arg = call.args[0] if call.args else None
                        if src_arg is not None and \
                                not _has_tmp_token(src_arg):
                            emit(Finding(
                                PASS, "rename-no-tmp", rel, fn.qual, d,
                                f"{d} from a non-scratch source: a "
                                "rename is only a commit protocol "
                                "over a same-dir tmp — use "
                                "persist.seal/atomic_write, or waive "
                                "(user-file move)",
                                call.lineno))
                        if d == "os.replace" and not has_fsync:
                            emit(Finding(
                                PASS, "replace-no-fsync", rel, fn.qual,
                                d,
                                "os.replace with no fsync in the same "
                                "function: durable in name only — the "
                                "persist seam orders fsync-file → "
                                "rename → fsync-dir per declared "
                                "policy",
                                call.lineno))
                if last in NAMED_APIS and ("persist." in d
                                           or d == last):
                    # only persist-receiver calls: `scratch`/`seal`
                    # are common words, so a bare name must resolve
                    # to an import from persist to count.
                    if d == last and not _imports_from_persist(
                            fn.src.tree, last):
                        continue
                    arg = call.args[0] if call.args else None
                    if not (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)):
                        emit(Finding(
                            PASS, "artifact-dynamic", rel, fn.qual,
                            "non-literal",
                            "artifact name must be a string literal "
                            "so the registry table and the crash "
                            "grid stay static",
                            call.lineno))
                        continue
                    if arg.value not in declared:
                        emit(Finding(
                            PASS, "artifact-undeclared", rel, fn.qual,
                            arg.value,
                            f"artifact {arg.value!r} is not declared "
                            "in spacedrive_tpu/persist.py "
                            "(declare_artifact)",
                            call.lineno))
        return findings


def _imports_from_persist(tree: ast.AST, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.rsplit(".", 1)[-1] == "persist":
            if any(a.name == name for a in node.names):
                return True
    return False
