"""Pass: queue-discipline — every cross-task channel is a declared,
bounded registry channel.

A bare `asyncio.Queue()` has no capacity, no overflow policy, no
metrics, and no owner: the moment its consumer stalls, the producer
absorbs unbounded memory (the pre-registry media actor queue could
swallow a whole library index behind one slow thumbnailer). The
discipline mirrors flags.py / timeouts.py: every channel is DECLARED
in `spacedrive_tpu/channels.py` (name, capacity, policy, owner;
README table via `--chan-table`) and constructed through
`channels.channel(name)` / `channels.window(name)` /
`channels.bounded_dict(name)`.

Codes:

- ``bare-queue`` — an `asyncio.Queue(...)` construction anywhere
  outside the central registry. There is no sanctioned bare queue:
  even flow-controlled ones must declare capacity and policy so the
  load-harness can audit (and scale) them in one place.
- ``unbounded-deque-channel`` — a `deque()` with no `maxlen` assigned
  to an instance/module attribute and used as a producer/consumer
  channel (the class both appends to it and pops from its head —
  the pre-registry jobs run-queue shape). Function-local deques are
  work lists, not channels, and are exempt.
- ``unregistered-put`` — `put_nowait` on a receiver known to be a
  bare (unregistered) queue: a self-attribute the class assigned a
  bare queue/deque, or a local variable assigned one in the same
  function. Receivers of unknown origin (parameters) are left to the
  construction-site rules.
- ``unregistered-send-buffer`` — a class that defines `send_nowait`
  (the buffered-transport idiom) without constructing a
  `channels.window(...)` in the same class: send_nowait's whole point
  is deferring the flush, so its buffer must be depth-tracked.
- ``undeclared-channel`` / ``dynamic-channel-name`` — a
  `channels.channel/window/bounded_dict` call whose name literal is
  missing from the registry, or is not a literal at all (the table
  must stay static) — exactly the timeout-discipline name rules.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from ..core import Finding, Project, SourceFile, dotted, own_body_walk

PASS = "queue-discipline"

CENTRAL = "spacedrive_tpu/channels.py"
_FACTORIES = {"channel", "window", "bounded_dict"}
_DEQUE_GROW = {"append", "appendleft", "extend"}
_DEQUE_DRAIN = {"popleft", "pop", "get_nowait"}


def declared_channels(root: str) -> Dict[str, Dict]:
    """Contracts from `declare_channel(...)` calls in the central
    registry (AST — the linted tree is never imported). Returns
    name → {capacity, policy, put_budget, kind, lineno}."""
    out: Dict[str, Dict] = {}
    path = os.path.join(root, CENTRAL)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) == "declare_channel" and node.args):
            continue
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            continue
        spec = {"capacity": 0, "policy": "", "put_budget": None,
                "kind": "queue", "lineno": node.lineno}
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            spec["capacity"] = int(node.args[1].value)
        if len(node.args) > 2 and isinstance(node.args[2], ast.Constant):
            spec["policy"] = str(node.args[2].value)
        for kw in node.keywords:
            if kw.arg in ("put_budget", "kind") and \
                    isinstance(kw.value, ast.Constant):
                spec[kw.arg] = kw.value.value
        out[name.value] = spec
    return out


def _is_bare_queue(call: ast.Call, src: SourceFile) -> bool:
    d = dotted(call.func)
    if d == "asyncio.Queue":
        return True
    if d == "Queue" and "from asyncio import" in src.src and \
            _imported_from(src.tree, "asyncio", "Queue"):
        return True
    return False


def _imported_from(tree: ast.Module, module: str, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            if any((a.asname or a.name) == name for a in node.names):
                return True
    return False


def _is_bare_deque(call: ast.Call) -> bool:
    d = dotted(call.func)
    if d not in ("deque", "collections.deque"):
        return False
    return not any(kw.arg == "maxlen" for kw in call.keywords)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a `self.x` attribute node."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _factory_call(call: ast.Call) -> Optional[str]:
    """The factory name for channels.channel/window/bounded_dict
    calls (bare or module-qualified), else None."""
    d = dotted(call.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    if last not in _FACTORIES:
        return None
    if "." in d and not d.startswith(("channels.", "self.")):
        return None
    return last


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        # attr → ("queue"|"deque", lineno) for bare constructions
        self.bare_attrs: Dict[str, tuple] = {}
        self.registered_attrs: Set[str] = set()
        self.deque_grow: Set[str] = set()
        self.deque_drain: Set[str] = set()
        self.defines_send_nowait = False
        self.has_window = False
        self.send_nowait_line = 0


class QueueDisciplinePass:
    name = PASS

    def run(self, project: Project) -> List[Finding]:
        declared = declared_channels(project.root)
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(f: Finding) -> None:
            if f.key() not in seen:
                seen.add(f.key())
                findings.append(f)

        for src in project.files:
            if src.relpath == CENTRAL:
                continue
            self._check_file(src, declared, emit)
        return findings

    # -- per-file ----------------------------------------------------------

    def _check_file(self, src: SourceFile, declared: Dict, emit) -> None:
        classes: Dict[str, _ClassInfo] = {}
        # class collection walk (includes nested defs: channel shape is
        # a class-wide property)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = self._scan_class(node)
        # constructions + name checks, everywhere
        cls_stack: List[str] = []
        self._walk(src, src.tree, cls_stack, classes, declared, emit,
                   qual="")

        for info in classes.values():
            if info.defines_send_nowait and not info.has_window:
                emit(Finding(
                    PASS, "unregistered-send-buffer", src.relpath,
                    f"{info.name}.send_nowait", info.name,
                    "class defines send_nowait without a "
                    "channels.window(...) depth tracker: the deferred "
                    "flush buffer must be declared and capped",
                    info.send_nowait_line))
            for attr, (kind, lineno) in info.bare_attrs.items():
                if kind != "deque":
                    continue
                if attr in info.deque_grow and attr in info.deque_drain:
                    emit(Finding(
                        PASS, "unbounded-deque-channel", src.relpath,
                        info.name, f"self.{attr}",
                        f"unbounded deque `self.{attr}` used as a "
                        "producer/consumer channel: declare it in "
                        "spacedrive_tpu/channels.py and construct via "
                        "channels.channel(name)",
                        lineno))

    def _scan_class(self, cls: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(cls.name)
        for node in ast.walk(cls):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "send_nowait":
                info.defines_send_nowait = True
                info.send_nowait_line = node.lineno
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    factory = _factory_call(value)
                    if factory is not None:
                        info.registered_attrs.add(attr)
                        if factory == "window":
                            info.has_window = True
                    elif dotted(value.func) == "asyncio.Queue":
                        info.bare_attrs[attr] = ("queue", value.lineno)
                    elif _is_bare_deque(value):
                        info.bare_attrs[attr] = ("deque", value.lineno)
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is None or not d.startswith("self."):
                    continue
                parts = d.split(".")
                if len(parts) != 3:
                    continue
                _self, attr, method = parts
                if method in _DEQUE_GROW:
                    info.deque_grow.add(attr)
                elif method in _DEQUE_DRAIN:
                    info.deque_drain.add(attr)
        return info

    # -- recursive walk with class context ----------------------------------

    def _walk(self, src: SourceFile, node: ast.AST, cls_stack: List[str],
              classes: Dict[str, _ClassInfo], declared: Dict, emit,
              qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                cls_stack.append(child.name)
                self._walk(src, child, cls_stack, classes, declared,
                           emit, qual=child.name)
                cls_stack.pop()
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{qual}.{child.name}" if qual else child.name
                self._check_fn(src, child, cls_stack, classes, declared,
                               emit, fq)
                self._walk(src, child, cls_stack, classes, declared,
                           emit, qual=fq)
                continue
            # module-level statements
            if isinstance(child, (ast.Assign, ast.Expr)):
                self._check_stmt(src, child, cls_stack, classes,
                                 declared, emit, qual, local_queues=set())
            self._walk(src, child, cls_stack, classes, declared, emit,
                       qual=qual)

    def _check_fn(self, src: SourceFile, fn: ast.AST,
                  cls_stack: List[str], classes: Dict, declared: Dict,
                  emit, qual: str) -> None:
        # Two phases: collect local bare-queue names first (the body
        # walk is unordered), then check call sites against them.
        local_queues: Set[str] = set()
        for node in own_body_walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    dotted(node.value.func) == "asyncio.Queue":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_queues.add(tgt.id)
        for node in own_body_walk(fn):
            self._check_stmt(src, node, cls_stack, classes, declared,
                             emit, qual, local_queues)

    def _check_stmt(self, src: SourceFile, node: ast.AST,
                    cls_stack: List[str], classes: Dict, declared: Dict,
                    emit, qual: str, local_queues: Set[str]) -> None:
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            if _is_bare_queue(call, src):
                emit(Finding(
                    PASS, "bare-queue", src.relpath, qual,
                    "asyncio.Queue",
                    "bare asyncio.Queue(): cross-task channels must be "
                    "declared in spacedrive_tpu/channels.py and "
                    "constructed via channels.channel(name)",
                    call.lineno))
            factory = _factory_call(call)
            if factory is not None:
                self._check_name(src, call, declared, emit, qual)
            d = dotted(call.func)
            if d is None:
                continue
            parts = d.split(".")
            if parts[-1] == "put_nowait":
                recv = parts[:-1]
                if len(recv) == 2 and recv[0] == "self" and cls_stack:
                    info = classes.get(cls_stack[-1])
                    if info is not None and recv[1] in info.bare_attrs:
                        emit(Finding(
                            PASS, "unregistered-put", src.relpath, qual,
                            f"self.{recv[1]}.put_nowait",
                            f"put_nowait on unregistered channel "
                            f"`self.{recv[1]}`: declare it in "
                            "channels.py so capacity and overflow "
                            "policy are auditable",
                            call.lineno))
                elif len(recv) == 1 and recv[0] in local_queues:
                    emit(Finding(
                        PASS, "unregistered-put", src.relpath, qual,
                        f"{recv[0]}.put_nowait",
                        f"put_nowait on unregistered local queue "
                        f"`{recv[0]}`: declare it in channels.py",
                        call.lineno))

    def _check_name(self, src: SourceFile, call: ast.Call,
                    declared: Dict, emit, qual: str) -> None:
        arg = call.args[0] if call.args else None
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            emit(Finding(
                PASS, "dynamic-channel-name", src.relpath, qual,
                "non-literal",
                "channel name must be a string literal so the "
                "registry table stays static",
                call.lineno))
            return
        if arg.value not in declared:
            emit(Finding(
                PASS, "undeclared-channel", src.relpath, qual,
                arg.value,
                f"channel {arg.value!r} is not declared in "
                "spacedrive_tpu/channels.py",
                call.lineno))
