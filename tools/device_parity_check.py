"""On-device digest parity check — run ALONE on the real chip.

The suite's Pallas tests are TPU-gated (skipped on the CPU mesh), so
this is the reproducible on-chip correctness artifact: the batched
full-file checksum pipeline (the jitted Pallas chunk stage + tree
reduction, ops/blake3_pallas.py) and the CAS path, both compared
byte-for-byte against the numpy oracle on edge-shaped inputs.

Usage: python tools/device_parity_check.py
Prints one JSON line {"ok": true, ...} on success; non-zero exit on any
digest mismatch.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    import jax

    from spacedrive_tpu.ops.blake3_batch import blake3_batch_np
    from spacedrive_tpu.ops.blake3_jax import (build_cas_messages,
                                               blake3_words,
                                               checksums_words_batched,
                                               digests_to_cas_ids)
    from spacedrive_tpu.ops.cas import cas_id_of_payload

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(5)

    # 1. batched full-file checksums across the boundary sizes
    blobs = [bytes(rng.integers(0, 256, n, dtype=np.uint8))
             for n in (0, 1, 1024, 1025, 70_000, 262_144)]
    got = checksums_words_batched(blobs)
    want = [d.hex() for d in blake3_batch_np(blobs)]
    checksum_ok = got == want

    # 2. CAS ids on the canonical large-file grid
    B = 64
    payloads = rng.integers(0, 256, size=(B, 57344), dtype=np.uint8)
    sizes = rng.integers(200_000, 5_000_000, size=B).astype(np.uint64)
    words, lengths = build_cas_messages(payloads, sizes)
    ids = digests_to_cas_ids(blake3_words(words, lengths))
    cas_ok = all(
        ids[i] == cas_id_of_payload(int(sizes[i]), payloads[i].tobytes())
        for i in (0, B // 2, B - 1))

    ok = checksum_ok and cas_ok
    print(json.dumps({"ok": ok, "platform": platform,
                      "checksum_parity": checksum_ok,
                      "cas_parity": cas_ok,
                      "checksum_cases": len(blobs), "cas_batch": B}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
