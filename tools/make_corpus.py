"""Deterministic synthetic corpus generator.

The reference's `packages/test-files/` is empty in the snapshot
(populated by external scripts), so the benchmark configs of
BASELINE.md must run against a generated corpus. This produces a
reproducible (seeded) tree with the properties the identification
pipeline cares about:

- a size mix straddling the 100 KiB sampled-hash threshold
  (cas.rs:15 semantics) with a long tail of multi-MiB files,
- exact duplicates at a configurable rate (CAS-ID dedup, config 3),
- near-duplicate images: base PNGs plus slightly-perturbed variants
  (pHash Hamming near-dup, config 4),
- nested directories for walker/rule coverage.

    python tools/make_corpus.py OUT_DIR --files 10000 --dup-rate 0.1 \
        --images 200 --seed 0
"""

from __future__ import annotations

import argparse
import json
import os
import random


def make_corpus(out_dir: str, files: int = 1000, dup_rate: float = 0.1,
                images: int = 0, seed: int = 0, depth: int = 3,
                small_only: bool = False) -> dict:
    """small_only caps files at 8 KiB — the 100k/1M-scale configs, where
    generating the default multi-MiB tail would dominate the run."""
    rng = random.Random(seed)
    os.makedirs(out_dir, exist_ok=True)
    dirs = [out_dir]
    for d in range(depth):
        for i in range(min(2 ** (d + 1), 8)):
            p = os.path.join(rng.choice(dirs), f"d{d}_{i}")
            os.makedirs(p, exist_ok=True)
            dirs.append(p)

    stats = {"files": 0, "bytes": 0, "duplicates": 0, "images": 0}
    blobs = []  # (payload reference) for duplicate sampling

    def size_sample() -> int:
        if small_only:
            return rng.randrange(256, 8 * 1024)
        r = rng.random()
        if r < 0.50:
            return rng.randrange(256, 100 * 1024)          # whole-file CAS
        if r < 0.90:
            return rng.randrange(100 * 1024 + 1, 1 << 20)  # sampled CAS
        return rng.randrange(1 << 20, 8 << 20)             # multi-MiB

    for i in range(files):
        path = os.path.join(rng.choice(dirs), f"f{i:06d}.bin")
        if blobs and rng.random() < dup_rate:
            src = rng.choice(blobs)
            with open(src, "rb") as f:
                payload = f.read()
            stats["duplicates"] += 1
        else:
            payload = rng.randbytes(size_sample())
        with open(path, "wb") as f:
            f.write(payload)
        blobs.append(path)
        if len(blobs) > 256:
            blobs.pop(0)
        stats["files"] += 1
        stats["bytes"] += len(payload)

    if images:
        from PIL import Image, ImageDraw

        img_dir = os.path.join(out_dir, "images")
        os.makedirs(img_dir, exist_ok=True)
        bases = max(1, images // 3)
        for b in range(bases):
            im = Image.new("RGB", (256, 192), (
                rng.randrange(256), rng.randrange(256), rng.randrange(256)))
            draw = ImageDraw.Draw(im)
            for _ in range(6):
                x0, y0 = rng.randrange(200), rng.randrange(150)
                draw.rectangle(
                    [x0, y0, x0 + rng.randrange(8, 56),
                     y0 + rng.randrange(8, 42)],
                    fill=(rng.randrange(256), rng.randrange(256),
                          rng.randrange(256)))
            im.save(os.path.join(img_dir, f"img{b:04d}.png"))
            stats["images"] += 1
            # near-dup variants: tiny brightness/crop perturbations that
            # keep the DCT signature close (Hamming ≤ threshold).
            for v in range((images - bases) // bases + 1):
                if stats["images"] >= images:
                    break
                var = im.point(lambda px, d=v: min(255, px + 2 + d))
                var.save(os.path.join(img_dir, f"img{b:04d}_v{v}.png"))
                stats["images"] += 1

    with open(os.path.join(out_dir, "corpus.json"), "w") as f:
        json.dump({"seed": seed, **stats}, f)
    return stats


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--files", type=int, default=1000)
    ap.add_argument("--dup-rate", type=float, default=0.1)
    ap.add_argument("--images", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args()
    print(json.dumps(make_corpus(args.out_dir, args.files, args.dup_rate,
                                 args.images, args.seed,
                                 small_only=args.small)))
