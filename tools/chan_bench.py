"""Producer/consumer burst microbench over the channel registry.

The backpressure analog of perf_smoke/sync_bench: drives two declared
bench channels through the same Channel machinery production uses and
emits a BENCH-style JSON artifact, so a regression in the registry's
hot path (put/get overhead, shed accounting, block-wait plumbing)
gates like a perf regression instead of surfacing as mystery latency
in the sync plane.

Two phases:

- **block phase** (`bench.chan`, policy block): a producer bursts
  items at a consumer draining at a fixed service rate; every put's
  wall time is recorded — depth high-water shows how far the window
  fills, put-block p99 shows the backpressure actually exerted.
- **shed phase** (`bench.shed`, policy shed_new): the consumer stalls
  entirely; the producer keeps bursting. Depth must pin at capacity
  and every overflow must land in the shed counter — the bounded-
  memory contract the stalled-consumer tier-1 test also asserts.

    python -m tools.chan_bench --json
    python -m tools.chan_bench --items 50000 --burst 512
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import Dict, List

from spacedrive_tpu import channels


def _p(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


async def _block_phase(items: int, burst: int) -> Dict:
    chan = channels.channel("bench.chan")
    put_times: List[float] = []
    consumed = 0

    async def consumer() -> None:
        nonlocal consumed
        while consumed < items:
            await chan.get()
            consumed += 1
            if consumed % burst == 0:
                # fixed service cadence: one loop tick per burst, so
                # the producer periodically runs into the bound
                await asyncio.sleep(0)

    async def producer() -> None:
        for i in range(items):
            t0 = time.perf_counter()
            await chan.put(i)
            put_times.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    cons = asyncio.ensure_future(consumer())
    await producer()
    await cons
    wall = time.perf_counter() - t0
    put_times.sort()
    return {
        "channel": "bench.chan",
        "policy": "block",
        "items": items,
        "wall_s": round(wall, 6),
        "puts_per_s": round(items / wall, 1) if wall else 0.0,
        "depth_high_water": chan.high_water,
        "capacity": chan.capacity,
        "put_block_p50_us": round(_p(put_times, 0.50) * 1e6, 2),
        "put_block_p99_us": round(_p(put_times, 0.99) * 1e6, 2),
        "shed_total": chan.shed_total,
    }


async def _shed_phase(items: int) -> Dict:
    chan = channels.channel("bench.shed")
    accepted = 0
    for i in range(items):  # consumer fully stalled: nobody drains
        if chan.put_nowait(i):
            accepted += 1
    assert len(chan) <= chan.capacity, "bounded-depth contract broken"
    return {
        "channel": "bench.shed",
        "policy": "shed_new",
        "items": items,
        "accepted": accepted,
        "depth_high_water": chan.high_water,
        "capacity": chan.capacity,
        "shed_total": chan.shed_total,
    }


async def run(items: int = 20000, burst: int = 256) -> Dict:
    block = await _block_phase(items, burst)
    shed = await _shed_phase(items)
    return {
        "bench": "chan_burst",
        "items": items,
        "burst": burst,
        "phases": {"block": block, "shed": shed},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.chan_bench",
        description="channel-registry producer/consumer burst bench")
    ap.add_argument("--items", type=int, default=20000)
    ap.add_argument("--burst", type=int, default=256)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    artifact = asyncio.run(run(args.items, args.burst))
    if args.as_json:
        print(json.dumps(artifact, indent=2))
    else:
        b = artifact["phases"]["block"]
        s = artifact["phases"]["shed"]
        print(f"block: {b['puts_per_s']:.0f} puts/s, depth hw "
              f"{b['depth_high_water']}/{b['capacity']}, put-block "
              f"p99 {b['put_block_p99_us']}us")
        print(f"shed:  {s['accepted']}/{s['items']} accepted, "
              f"{s['shed_total']:.0f} shed, depth hw "
              f"{s['depth_high_water']}/{s['capacity']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
