"""Perf-trajectory collation: one table over every committed BENCH
round.

The repo records one `BENCH_rNN.json` per growth round, but the
artifact shape evolved with the harnesses: r01–r05 are driver-wrapped
kernel benches (`{n, cmd, rc, tail, parsed}`), r06 wraps an
overlap_bench sweep, r07 wraps a fleet-observatory snapshot, r08/r09
are raw load_bench artifacts, r10 is a raw overlap_bench artifact.
Reading the trajectory therefore meant opening ten files with four
schemas. This tool normalizes every round into one row — headline
metric, unit, and the round's own gate/validity verdict — validates
each against its shape (exit 1 on any schema problem: the committed
history must stay machine-readable), and renders the markdown table
the README perf section embeds between its `bench-trend` markers.

    python -m tools.bench_trend                 # table to stdout
    python -m tools.bench_trend --json          # rows as JSON
    python -m tools.bench_trend --write-readme  # splice into README.md
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BEGIN = "<!-- bench-trend:begin (generated: python -m tools.bench_trend --write-readme) -->"
END = "<!-- bench-trend:end -->"


def load_rounds(root: str) -> List[Tuple[int, Dict[str, Any]]]:
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        with open(path, encoding="utf-8") as f:
            rounds.append((int(m.group(1)), json.load(f)))
    return rounds


def _fmt(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}M"
    if v >= 1e4:
        return f"{v / 1e3:.0f}k"
    if v >= 100:
        return f"{v:.0f}"
    return f"{v:g}"


def normalize(n: int, doc: Dict[str, Any]) -> Dict[str, Any]:
    """One BENCH round -> one row. `problems` non-empty means the
    committed artifact no longer matches its declared shape."""
    row: Dict[str, Any] = {"round": n, "bench": "?", "value": None,
                           "unit": "", "note": "", "problems": []}
    probs = row["problems"]

    # Driver-wrapped rounds carry the real artifact under `parsed`.
    if "parsed" in doc and isinstance(doc.get("parsed"), dict):
        if doc.get("rc") not in (0, None):
            probs.append(f"r{n:02d}: recorded rc={doc.get('rc')}")
        doc = doc["parsed"]

    metric = doc.get("metric") or doc.get("bench")
    if metric == "cas_ids_per_sec_large_files":
        row["bench"] = "kernel CAS-ID"
        row["unit"] = doc.get("unit") or "files/s"
        row["value"] = doc.get("value")
        if not isinstance(row["value"], (int, float)) or row["value"] <= 0:
            probs.append(f"r{n:02d}: kernel value missing")
        vs = doc.get("vs_baseline")
        if isinstance(vs, (int, float)):
            row["note"] = f"{vs:g}x native baseline"
    elif metric == "overlap_bench":
        row["bench"] = "overlap pipeline"
        row["unit"] = doc.get("unit") or "files/s"
        sweep = doc.get("sweep")
        if not isinstance(sweep, list) or not sweep:
            probs.append(f"r{n:02d}: overlap sweep missing")
        else:
            best = max(sweep,
                       key=lambda s: s.get("measured_files_per_sec") or 0)
            row["value"] = best.get("measured_files_per_sec")
            ratio = best.get("ratio")
            row["note"] = (f"depth {best.get('depth')}, "
                           f"{ratio:.0%} of component bound"
                           if isinstance(ratio, (int, float)) else
                           f"depth {best.get('depth')}")
            if not isinstance(row["value"], (int, float)):
                probs.append(f"r{n:02d}: overlap measured rate missing")
    elif metric == "fleet_observatory":
        row["bench"] = "fleet observatory"
        nodes = doc.get("nodes")
        row["unit"] = "nodes"
        row["value"] = len(nodes) if isinstance(nodes, list) else None
        remote = doc.get("remote_row") or {}
        row["note"] = ("remote reachable"
                       if remote.get("reachable") else "remote stale")
        if row["value"] is None:
            probs.append(f"r{n:02d}: fleet nodes missing")
    elif metric == "load_bench":
        row["bench"] = "fleet load storm"
        row["unit"] = "ops/s"
        pull = (doc.get("workloads") or {}).get("pull_storm") or {}
        row["value"] = pull.get("ops_per_s")
        gate = doc.get("gate") or {}
        notes = ["gate PASS" if gate.get("passed") else "gate FAIL"]
        inc = doc.get("incidents")
        if isinstance(inc, dict):
            notes.append(f"{len(inc.get('headers') or [])} incident "
                         "bundle(s)")
        row["note"] = ", ".join(notes)
        if not gate.get("passed"):
            probs.append(f"r{n:02d}: recorded load_bench gate failed")
        if not isinstance(row["value"], (int, float)):
            probs.append(f"r{n:02d}: pull_storm rate missing")
    else:
        probs.append(f"r{n:02d}: unrecognized artifact shape "
                     f"(metric={metric!r})")
    return row


def render_table(rows: List[Dict[str, Any]]) -> str:
    out = ["| Round | Bench | Headline | Notes |",
           "|---|---|---|---|"]
    for r in rows:
        v = (f"{_fmt(r['value'])} {r['unit']}"
             if isinstance(r["value"], (int, float)) else "—")
        out.append(f"| r{r['round']:02d} | {r['bench']} | {v} "
                   f"| {r['note']} |")
    return "\n".join(out)


def write_readme(table: str, readme_path: str) -> bool:
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    if BEGIN not in text or END not in text:
        print(f"bench_trend: no {BEGIN!r} markers in {readme_path}",
              file=sys.stderr)
        return False
    head, rest = text.split(BEGIN, 1)
    _, tail = rest.split(END, 1)
    new = f"{head}{BEGIN}\n{table}\n{END}{tail}"
    if new != text:
        with open(readme_path, "w", encoding="utf-8") as f:
            f.write(new)
    return True


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Collate BENCH_r*.json rounds into the perf "
                    "trajectory table")
    ap.add_argument("--root", default=ROOT)
    ap.add_argument("--json", action="store_true",
                    help="emit normalized rows as JSON")
    ap.add_argument("--write-readme", action="store_true",
                    help="splice the table between README.md's "
                         "bench-trend markers")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.root)
    if not rounds:
        print("bench_trend: no BENCH_r*.json found", file=sys.stderr)
        return 1
    rows = [normalize(n, doc) for n, doc in rounds]
    problems = [p for r in rows for p in r["problems"]]
    for p in problems:
        print(f"bench_trend: SCHEMA: {p}", file=sys.stderr)

    if args.json:
        print(json.dumps({"metric": "bench_trend", "rows": rows}))
    else:
        table = render_table(rows)
        if args.write_readme:
            if not write_readme(table,
                                os.path.join(args.root, "README.md")):
                return 1
            print(f"bench_trend: wrote {len(rows)} rows into README.md",
                  file=sys.stderr)
        else:
            print(table)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
