"""Build minimal-but-valid HEIC fixtures for the extraction tests.

No HEVC encoder exists in this image, so the fixtures mirror the real
container shape (ftyp/meta/iloc/iinf/iref/mdat per ISO 14496-12 +
23008-12) with an hvc1 primary item whose payload is opaque, plus the
payloads the extractor actually reads:

    fixture "thumb":  a JPEG-coded item `thmb`-referencing the primary
    fixture "exif":   an Exif item whose TIFF IFD1 embeds a JPEG
                      thumbnail (the every-camera convention)

    python tools/make_heif_fixture.py <out_dir>
"""

from __future__ import annotations

import io
import struct
import sys


def box(typ: bytes, payload: bytes) -> bytes:
    return struct.pack(">I4s", 8 + len(payload), typ) + payload


def full_box(typ: bytes, version: int, flags: int, payload: bytes) -> bytes:
    return box(typ, struct.pack(">I", (version << 24) | flags) + payload)


def make_jpeg(size=(64, 48), color=(200, 80, 20)) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", size, color).save(buf, "JPEG", quality=80)
    return buf.getvalue()


def make_exif_with_thumbnail(jpeg: bytes) -> bytes:
    """ExifDataBlock: u32 tiff offset + "Exif\0\0" + TIFF with IFD0 and
    an IFD1 carrying JPEGInterchangeFormat/Length."""
    # TIFF (big-endian MM)
    # layout: header(8) IFD0(2+12+4) IFD1(2+2*12+4) jpeg
    ifd0_off = 8
    ifd0 = struct.pack(">H", 1)
    ifd0 += struct.pack(">HHI4s", 0x0131, 2, 4, b"sd\x00\x00")  # Software
    ifd1_off = ifd0_off + 2 + 12 + 4
    ifd0 += struct.pack(">I", ifd1_off)
    jpeg_off = ifd1_off + 2 + 2 * 12 + 4
    ifd1 = struct.pack(">H", 2)
    ifd1 += struct.pack(">HHII", 0x0201, 4, 1, jpeg_off)
    ifd1 += struct.pack(">HHII", 0x0202, 4, 1, len(jpeg))
    ifd1 += struct.pack(">I", 0)
    tiff = b"MM\x00\x2a" + struct.pack(">I", ifd0_off) + ifd0 + ifd1 + jpeg
    return struct.pack(">I", 0) + b"Exif\x00\x00" + tiff


def _infe(item_id: int, item_type: bytes, content_type: str = "") -> bytes:
    payload = struct.pack(">HH4s", item_id, 0, item_type) + b"\x00"
    if content_type:
        payload += content_type.encode() + b"\x00"
    return full_box(b"infe", 2, 0, payload)


def make_heic(items: list[tuple[int, bytes, str, bytes]],
              primary: int,
              refs: list[tuple[bytes, int, list[int]]] = (),
              ispe: tuple[int, int] | None = (64, 48)) -> bytes:
    """items: (item_id, item_type, content_type, payload)."""
    ftyp = box(b"ftyp", b"heic\x00\x00\x00\x00" + b"heicmif1")

    # mdat payload layout (offsets resolved after meta size is known)
    payloads = [p for _, _, _, p in items]

    def meta_box(mdat_file_off: int) -> bytes:
        hdlr = full_box(b"hdlr", 0, 0,
                        b"\x00" * 4 + b"pict" + b"\x00" * 12 + b"\x00")
        pitm = full_box(b"pitm", 0, 0, struct.pack(">H", primary))
        iinf = full_box(
            b"iinf", 0, 0, struct.pack(">H", len(items)) + b"".join(
                _infe(iid, t, ct) for iid, t, ct, _ in items))
        # iloc v0: offset_size=4, length_size=4, base_offset_size=0
        entries = b""
        off = mdat_file_off + 8  # into the mdat payload
        for (iid, _t, _ct, payload) in items:
            entries += struct.pack(">HHH", iid, 0, 1)
            entries += struct.pack(">II", off, len(payload))
            off += len(payload)
        iloc = full_box(b"iloc", 0, 0,
                        struct.pack(">HH", 0x4400, len(items)) + entries)
        parts = hdlr + pitm + iinf + iloc
        if ispe is not None:
            parts += box(b"iprp", box(b"ipco", full_box(
                b"ispe", 0, 0, struct.pack(">II", *ispe))))
        if refs:
            refpay = b""
            for rtype, from_id, to_ids in refs:
                refpay += box(rtype, struct.pack(
                    ">HH", from_id, len(to_ids)) + b"".join(
                    struct.pack(">H", t) for t in to_ids))
            parts += full_box(b"iref", 0, 0, refpay)
        return full_box(b"meta", 0, 0, parts)

    # two passes: meta size depends only on counts, not offsets
    probe = meta_box(0)
    mdat_off = len(ftyp) + len(probe)
    meta = meta_box(mdat_off)
    assert len(meta) == len(probe)
    mdat = box(b"mdat", b"".join(payloads))
    return ftyp + meta + mdat


def write_fixtures(out_dir: str) -> dict:
    import os

    os.makedirs(out_dir, exist_ok=True)
    jpeg = make_jpeg()
    fake_hevc = b"\x00\x00\x00\x01hevc-payload-not-decodable" * 8

    thumb = make_heic(
        items=[(1, b"hvc1", "", fake_hevc),
               (2, b"jpeg", "", jpeg)],
        primary=1,
        refs=[(b"thmb", 2, [1])])
    with open(os.path.join(out_dir, "embedded_thumb.heic"), "wb") as f:
        f.write(thumb)

    exif_payload = make_exif_with_thumbnail(make_jpeg(color=(20, 80, 200)))
    exif = make_heic(
        items=[(1, b"hvc1", "", fake_hevc),
               (2, b"Exif", "", exif_payload)],
        primary=1,
        refs=[(b"cdsc", 2, [1])])
    with open(os.path.join(out_dir, "exif_thumb.heic"), "wb") as f:
        f.write(exif)

    return {"embedded_thumb.heic": len(thumb), "exif_thumb.heic": len(exif)}


if __name__ == "__main__":
    print(write_fixtures(sys.argv[1] if len(sys.argv) > 1
                         else "tests/fixtures"))
