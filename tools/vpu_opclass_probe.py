"""Instruction-mix floor probe for the CAS kernel (round-4 accounting).

Question (VERDICT r3 item 7): is the kernel's measured ~2.5M files/s at
its instruction-mix floor, or is there headroom? Answer it by measuring
the floor DIRECTLY: chain the kernel's own 7-round BLAKE3 compression
body (`blake3_batch.compress_cv` — adds, xors, shift+or rotations,
diagonal rolls; nothing else: no message staging, no chunk masking,
no tree reduce) behind a non-foldable carry, fit the marginal time per
compression exactly as tools/kernel_ceiling.py fits the full kernel
(two chain lengths split fixed RPC from marginal compute), and convert:

    floor_files_per_sec = 1 / (t_compress * compressions_per_file)

A large-mode CAS file is 57 chunks x 16 blocks + 56 tree parents
= 968 compressions. If the full kernel's measured marginal rate is
within ~15% of this pure-ALU floor, the remaining 1-utilization is the
compression math itself (the VPU lowering of rotate as shift+shift+or,
roll data movement), not schedulable overhead — the accounting the
round-3 verdict asked to see. Static op count per compression (the
x-axis of that accounting): 7 rounds x 2 vector-G x 4 words x
(6 add + 4 xor + 4 rot x 3) + 6 rolls/round + output fold
= 1,232 ALU ops (+ the 8-xor output fold = 1,240) + 168
roll-moves per 64-byte block.

Run ALONE — the tunnel is single-client. Chunked dispatches of a few
seconds; D2H fetch is the only real sync on this backend.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

COMPRESSIONS_PER_FILE = 57 * 16 + 56  # chunk blocks + tree parents
ALU_OPS_PER_COMPRESSION = 1240
ROLL_MOVES_PER_COMPRESSION = 168


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from spacedrive_tpu.ops.blake3_batch import compress_cv

    B, C = 2048, 57  # the production large-mode grid slice
    rng = np.random.default_rng(0)
    cv0 = [rng.integers(0, 2**32, (B, C), dtype=np.uint32)
           for _ in range(8)]
    m0 = [rng.integers(0, 2**32, (B, C), dtype=np.uint32)
          for _ in range(16)]

    UNROLL = 4

    def make(iters: int):
        @jax.jit
        def f(cv, m):
            def step(carry, _):
                out = list(carry)
                for k in range(UNROLL):
                    # crypto chaining: nothing here constant-folds
                    out = compress_cv(jnp, out, m, out[0], out[1],
                                      jnp.uint32(64), jnp.uint32(0))
                return tuple(out), None
            out, _ = lax.scan(step, tuple(cv), None, length=iters)
            return out[0]
        return f

    def timed(f, cv, m):
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            _ = np.asarray(f(cv, m)).ravel()[0]  # D2H = the only sync
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    cvd = [jnp.asarray(a) for a in cv0]
    md = [jnp.asarray(a) for a in m0]
    rows = []
    for iters in (256, 1024):
        f = make(iters)
        _ = np.asarray(f(cvd, md)).ravel()[0]  # compile+warm
        dt = timed(f, cvd, md)
        n_compress = iters * UNROLL * B * C
        rows.append((iters, dt, n_compress))
        print(json.dumps({
            "probe": "compress_chain", "iters": iters, "unroll": UNROLL,
            "seconds": round(dt, 4),
            "compressions": n_compress,
        }), flush=True)

    # fit: dt = t_fixed + n_compress * t_marg  (two points)
    (i1, dt1, n1), (i2, dt2, n2) = rows
    t_marg = (dt2 - dt1) / (n2 - n1)
    t_fixed = dt1 - n1 * t_marg
    compress_rate = 1.0 / t_marg
    alu_rate = compress_rate * ALU_OPS_PER_COMPRESSION
    floor_files = compress_rate / COMPRESSIONS_PER_FILE
    print(json.dumps({
        "metric": "cas_instruction_mix_floor",
        "t_fixed_ms": round(t_fixed * 1e3, 2),
        "t_marginal_ns_per_compression": round(t_marg * 1e9, 3),
        "compressions_per_sec": f"{compress_rate:.4e}",
        "alu_u32_ops_per_sec": f"{alu_rate:.4e}",
        "alu_ops_per_compression": ALU_OPS_PER_COMPRESSION,
        "roll_moves_per_compression": ROLL_MOVES_PER_COMPRESSION,
        "compressions_per_file": COMPRESSIONS_PER_FILE,
        "floor_files_per_sec": f"{floor_files:.4e}",
        "note": "pure-ALU compression chain, no staging/masking/tree; "
                "compare to the full kernel's measured marginal "
                "(tools/kernel_ceiling.py, ~2.5M files/s r3)",
    }), flush=True)


if __name__ == "__main__":
    main()
