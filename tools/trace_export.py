"""Chrome-trace exporter CLI: the flight recorder's operator face.

Turns the span ring (spacedrive_tpu/tracing.py) plus the pipeline
timeline (spacedrive_tpu/flight.py) into a schema-valid Chrome-trace/
Perfetto JSON artifact, and VALIDATES every document it touches — the
schema gate (`flight.validate_chrome_trace`) is the same one the
golden-file test pins, so a malformed trace fails here (exit 1), not
on the bench host.

    python -m tools.trace_export --json                # self-check
    python -m tools.trace_export --json --out t.json   # + write it
    python -m tools.trace_export --url http://host:port --out t.json
    python -m tools.trace_export --input exported.json # validate only
    python -m tools.trace_export --fleet --json        # fleet-merge self-check
    python -m tools.trace_export --fleet --trace-id ID --url http://host:port

- `--json` runs the built-in SELF-CHECK: a synthetic two-batch
  pipeline timeline plus a nested span tree goes through the real
  recorder + exporter, the result is validated and printed as JSON.
  Non-zero exit on any schema violation — tier-1 runs this so the
  exporter cannot rot silently.
- `--url` pulls a LIVE node's trace over the rspc HTTP route
  (`GET /rspc/node.trace.export`), validates, and writes it — the
  operator path for "what was that node just doing".
- `--input` validates an existing artifact (CI gating a stored trace).
- `--fleet` switches to the fleet observatory: with `--url` +
  `--trace-id` it pulls ONE assembled multi-node trace from
  `fleet.trace.export` (the serving node fetches every paired peer's
  obs.trace slice and merges the lanes, skew-aligned); with `--json`
  it runs the fleet-merge SELF-CHECK — two synthetic node captures
  with a known clock skew go through `flight.fleet_chrome_trace`, and
  the result must validate with both per-node pid lanes present, the
  skew recorded in metadata, and the remote lane shifted onto the
  local axis.

Open the artifact in chrome://tracing or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_self_check_trace() -> dict:
    """A deterministic exporter input exercising every lane kind: a
    nested + a cross-"node" continued span tree through the real
    tracing machinery, and a two-batch two-device pipeline timeline
    through a private FlightRecorder (the process one is left alone)."""
    from spacedrive_tpu import flight, tracing

    with tracing.span("rpc/trace.selfCheck"):
        tp = tracing.traceparent()
        with tracing.span("job/self-check"):
            with tracing.span("job.step", step=1):
                pass
    # The continued half: what a remote node's spans look like.
    with tracing.continue_trace(tp):
        with tracing.span("sync.pull", library="self-check"):
            pass

    rec = flight.FlightRecorder()
    run = flight.new_run_token()
    t0 = time.perf_counter()
    for batch, dev in ((1, "0"), (2, "1")):
        b = t0 + batch * 0.010
        rec.record("stage", batch=batch, t0=b, t1=b + 0.004,
                   stream=batch % 2, trace="selfcheck", run=run)
        rec.record("h2d", batch=batch, t0=b + 0.004, t1=b + 0.007,
                   device=dev, trace="selfcheck", run=run)
        rec.record("kernel", batch=batch, t0=b + 0.007, t1=b + 0.008,
                   device=dev, trace="selfcheck", run=run)
        rec.record("retire", batch=batch, t0=b + 0.008, t1=b + 0.009,
                   trace="selfcheck", run=run)
    spans = [r for r in tracing.recent_spans(
        limit=tracing.span_ring_capacity()) if "ts_us" in r]
    return flight.chrome_trace(spans=spans, timeline=rec.snapshot(),
                               node_name="self-check")


def build_fleet_self_check_trace() -> dict:
    """Deterministic fleet-merge input: two synthetic node captures —
    a 'serving' node with an rpc span + pipeline timeline and a
    'remote' node whose clock runs a known 2 s ahead — through the
    real merger. The remote lane must come out shifted onto the local
    axis with the skew recorded in metadata; fleet_problems() is the
    gate."""
    from spacedrive_tpu import flight, tracing

    with tracing.span("rpc/fleet.traceSelfCheck"):
        tp = tracing.traceparent()
        tid = tracing.current_trace_id()
    local_spans = [r for r in tracing.recent_spans(limit=8)
                   if r.get("trace") == tid]

    # The remote node's half: spans continued across the "wire", with
    # every wall timestamp 2 s in the future (its clock runs ahead).
    skew_s = 2.0
    with tracing.continue_trace(tp):
        with tracing.span("sync.pull", library="fleet-self-check"):
            pass
    remote_spans = []
    for r in tracing.recent_spans(limit=8):
        if r.get("trace") == tid and r.get("span") == "sync.pull":
            r = dict(r)
            r["ts_us"] = int(r["ts_us"] + skew_s * 1e6)
            remote_spans.append(r)

    rec = flight.FlightRecorder()
    run = flight.new_run_token()
    t0 = time.perf_counter()
    rec.record("stage", batch=1, t0=t0, t1=t0 + 0.004, trace=tid,
               run=run)
    rec.record("h2d", batch=1, t0=t0 + 0.004, t1=t0 + 0.007,
               device="0", trace=tid, run=run)
    rec.record("kernel", batch=1, t0=t0 + 0.007, t1=t0 + 0.008,
               device="0", trace=tid, run=run)
    rec.record("retire", batch=1, t0=t0 + 0.008, t1=t0 + 0.009,
               trace=tid, run=run)

    return flight.fleet_chrome_trace(
        [{"node": "local", "spans": local_spans,
          "timeline": rec.snapshot(), "skew_s": 0.0},
         {"node": "remote", "spans": remote_spans, "timeline": [],
          "skew_s": skew_s}],
        trace=tid, fleet_name="fleet self-check")


def fleet_problems(doc: dict) -> list:
    """Semantic gate over an assembled fleet trace, on top of the
    schema gate: per-node lanes present and the skew metadata
    recorded — what --fleet --json pins in tier-1."""
    from spacedrive_tpu import flight

    problems = flight.validate_chrome_trace(doc)
    other = doc.get("otherData", {}) if isinstance(doc, dict) else {}
    names = other.get("nodes")
    if not isinstance(names, list) or len(names) < 2:
        problems.append(f"fleet trace: want >=2 node lanes, got "
                        f"{names!r}")
        return problems
    if not isinstance(other.get("clock_skew_s"), dict):
        problems.append("fleet trace: clock_skew_s metadata missing")
    for i, name in enumerate(names):
        pid_spans = 2 * i + 1
        if not any(ev.get("ph") == "X" and ev.get("pid") == pid_spans
                   for ev in doc.get("traceEvents", [])):
            problems.append(
                f"fleet trace: node {name} contributed no span events")
    tid = other.get("trace")
    if tid:
        traces = {ev.get("args", {}).get("trace")
                  for ev in doc.get("traceEvents", [])
                  if ev.get("ph") == "X"
                  and isinstance(ev.get("pid"), int)
                  and ev["pid"] % 2 == 1}
        if traces - {tid}:
            problems.append(
                f"fleet trace: span lanes carry foreign trace ids "
                f"{sorted(traces - {tid})}")
    return problems


def fetch_fleet_trace(url: str, trace_id: str) -> dict:
    """GET /rspc/fleet.trace.export for one trace id from a live
    node's API host (the node assembles across its paired peers)."""
    import urllib.parse

    q = urllib.parse.quote(json.dumps({"trace": trace_id}))
    endpoint = url.rstrip("/") + "/rspc/fleet.trace.export?input=" + q
    with urllib.request.urlopen(endpoint, timeout=120) as resp:
        payload = json.load(resp)
    doc = payload.get("result") if isinstance(payload, dict) else None
    if doc is None:
        raise SystemExit(f"no result in response from {endpoint}")
    return doc


def fetch_live_trace(url: str) -> dict:
    """GET /rspc/node.trace.export from a live node's API host."""
    endpoint = url.rstrip("/") + "/rspc/node.trace.export"
    with urllib.request.urlopen(endpoint, timeout=30) as resp:
        payload = json.load(resp)
    doc = payload.get("result") if isinstance(payload, dict) else None
    if doc is None:
        raise SystemExit(f"no result in response from {endpoint}")
    return doc


def main(argv=None) -> int:
    from spacedrive_tpu import flight

    ap = argparse.ArgumentParser(
        description="Export/validate flight-recorder Chrome traces")
    ap.add_argument("--json", action="store_true",
                    help="build the self-check trace, validate it, and "
                         "print it as JSON (exit 1 on schema violation)")
    ap.add_argument("--url", default="", metavar="http://host:port",
                    help="pull a live node's node.trace.export, "
                         "validate, and write/print it")
    ap.add_argument("--input", default="", metavar="PATH",
                    help="validate an existing Chrome-trace JSON file")
    ap.add_argument("--out", default="", metavar="PATH",
                    help="write the (validated) trace document here")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: assembled multi-node traces "
                         "(--url needs --trace-id; --json runs the "
                         "fleet-merge self-check)")
    ap.add_argument("--trace-id", default="", metavar="HEX",
                    help="trace id to assemble across the fleet "
                         "(--fleet --url mode)")
    args = ap.parse_args(argv)

    if sum(map(bool, (args.json, args.url, args.input))) != 1:
        ap.error("exactly one of --json / --url / --input is required")

    if args.fleet and args.url and not args.trace_id:
        ap.error("--fleet --url needs --trace-id (which trace to "
                 "assemble)")

    if args.json:
        doc = build_fleet_self_check_trace() if args.fleet \
            else build_self_check_trace()
    elif args.url:
        doc = fetch_fleet_trace(args.url, args.trace_id) if args.fleet \
            else fetch_live_trace(args.url)
    else:
        try:
            with open(args.input, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"trace_export: unreadable {args.input}: {e}",
                  file=sys.stderr)
            return 1

    problems = fleet_problems(doc) if args.fleet \
        else flight.validate_chrome_trace(doc)
    for p in problems:
        print(f"trace_export: SCHEMA: {p}", file=sys.stderr)
    if problems:
        print(f"trace_export: {len(problems)} schema violation(s)",
              file=sys.stderr)
        return 1

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        print(f"trace_export: wrote {args.out} "
              f"({len(doc['traceEvents'])} events)", file=sys.stderr)
    if args.json:
        print(json.dumps(doc))
    elif not args.out:
        print(f"trace_export: valid "
              f"({len(doc['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
