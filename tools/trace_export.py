"""Chrome-trace exporter CLI: the flight recorder's operator face.

Turns the span ring (spacedrive_tpu/tracing.py) plus the pipeline
timeline (spacedrive_tpu/flight.py) into a schema-valid Chrome-trace/
Perfetto JSON artifact, and VALIDATES every document it touches — the
schema gate (`flight.validate_chrome_trace`) is the same one the
golden-file test pins, so a malformed trace fails here (exit 1), not
on the bench host.

    python -m tools.trace_export --json                # self-check
    python -m tools.trace_export --json --out t.json   # + write it
    python -m tools.trace_export --url http://host:port --out t.json
    python -m tools.trace_export --input exported.json # validate only

- `--json` runs the built-in SELF-CHECK: a synthetic two-batch
  pipeline timeline plus a nested span tree goes through the real
  recorder + exporter, the result is validated and printed as JSON.
  Non-zero exit on any schema violation — tier-1 runs this so the
  exporter cannot rot silently.
- `--url` pulls a LIVE node's trace over the rspc HTTP route
  (`GET /rspc/node.trace.export`), validates, and writes it — the
  operator path for "what was that node just doing".
- `--input` validates an existing artifact (CI gating a stored trace).

Open the artifact in chrome://tracing or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_self_check_trace() -> dict:
    """A deterministic exporter input exercising every lane kind: a
    nested + a cross-"node" continued span tree through the real
    tracing machinery, and a two-batch two-device pipeline timeline
    through a private FlightRecorder (the process one is left alone)."""
    from spacedrive_tpu import flight, tracing

    with tracing.span("rpc/trace.selfCheck"):
        tp = tracing.traceparent()
        with tracing.span("job/self-check"):
            with tracing.span("job.step", step=1):
                pass
    # The continued half: what a remote node's spans look like.
    with tracing.continue_trace(tp):
        with tracing.span("sync.pull", library="self-check"):
            pass

    rec = flight.FlightRecorder()
    run = flight.new_run_token()
    t0 = time.perf_counter()
    for batch, dev in ((1, "0"), (2, "1")):
        b = t0 + batch * 0.010
        rec.record("stage", batch=batch, t0=b, t1=b + 0.004,
                   stream=batch % 2, trace="selfcheck", run=run)
        rec.record("h2d", batch=batch, t0=b + 0.004, t1=b + 0.007,
                   device=dev, trace="selfcheck", run=run)
        rec.record("kernel", batch=batch, t0=b + 0.007, t1=b + 0.008,
                   device=dev, trace="selfcheck", run=run)
        rec.record("retire", batch=batch, t0=b + 0.008, t1=b + 0.009,
                   trace="selfcheck", run=run)
    spans = [r for r in tracing.recent_spans(
        limit=tracing.span_ring_capacity()) if "ts_us" in r]
    return flight.chrome_trace(spans=spans, timeline=rec.snapshot(),
                               node_name="self-check")


def fetch_live_trace(url: str) -> dict:
    """GET /rspc/node.trace.export from a live node's API host."""
    endpoint = url.rstrip("/") + "/rspc/node.trace.export"
    with urllib.request.urlopen(endpoint, timeout=30) as resp:
        payload = json.load(resp)
    doc = payload.get("result") if isinstance(payload, dict) else None
    if doc is None:
        raise SystemExit(f"no result in response from {endpoint}")
    return doc


def main(argv=None) -> int:
    from spacedrive_tpu import flight

    ap = argparse.ArgumentParser(
        description="Export/validate flight-recorder Chrome traces")
    ap.add_argument("--json", action="store_true",
                    help="build the self-check trace, validate it, and "
                         "print it as JSON (exit 1 on schema violation)")
    ap.add_argument("--url", default="", metavar="http://host:port",
                    help="pull a live node's node.trace.export, "
                         "validate, and write/print it")
    ap.add_argument("--input", default="", metavar="PATH",
                    help="validate an existing Chrome-trace JSON file")
    ap.add_argument("--out", default="", metavar="PATH",
                    help="write the (validated) trace document here")
    args = ap.parse_args(argv)

    if sum(map(bool, (args.json, args.url, args.input))) != 1:
        ap.error("exactly one of --json / --url / --input is required")

    if args.json:
        doc = build_self_check_trace()
    elif args.url:
        doc = fetch_live_trace(args.url)
    else:
        try:
            with open(args.input, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"trace_export: unreadable {args.input}: {e}",
                  file=sys.stderr)
            return 1

    problems = flight.validate_chrome_trace(doc)
    for p in problems:
        print(f"trace_export: SCHEMA: {p}", file=sys.stderr)
    if problems:
        print(f"trace_export: {len(problems)} schema violation(s)",
              file=sys.stderr)
        return 1

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        print(f"trace_export: wrote {args.out} "
              f"({len(doc['traceEvents'])} events)", file=sys.stderr)
    if args.json:
        print(json.dumps(doc))
    elif not args.out:
        print(f"trace_export: valid "
              f"({len(doc['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
