"""Fleet self-check peer: one real node process serving obs.* over
rspc HTTP with known seeded saturations.

The `sd_top --fleet --json` self-check (tier-1) needs a REMOTE node —
a separate process with its own telemetry registry, span ring, and
flight recorder — so per-(node, subsystem) attribution is proven
against genuinely separate state, not two views of one process. This
helper is that peer:

    python -m tools.fleet_peer --name peer-b --trace <hex id>

Boots a Node in a temp dir under `--name`, starts the rspc HTTP host
on an ephemeral port, seeds the same three saturations the sd_top
self-check has always used (a shedding bench channel, a slow store
write lock, a fired p2p.ping budget), records spans + a two-phase
pipeline timeline under `--trace` (so assembled fleet traces carry
this node's lanes), then prints ONE JSON line
``{"port": ..., "id": ..., "name": ...}`` and parks until stdin
closes — the parent's handle for teardown.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

try:
    # Seed the objects package: in runtimes without `cryptography` the
    # first attempt fails but leaves the non-crypto submodules cached,
    # after which mount_router imports cleanly (container quirk; no-op
    # where the dependency exists).
    import spacedrive_tpu.objects  # noqa: F401
except ModuleNotFoundError:
    pass


def seed_saturations() -> None:
    """The three known saturations, through the real registry (same
    set as tools/sd_top.py build_self_check, so the fleet gate asserts
    the same attribution names on the remote row)."""
    from spacedrive_tpu import channels
    from spacedrive_tpu.telemetry import (
        STORE_WRITE_LOCK_WAIT_SECONDS,
        TIMEOUTS_FIRED,
    )

    ch = channels.channel("bench.shed")
    for i in range(2 * ch.capacity):
        ch.put_nowait(i)
    STORE_WRITE_LOCK_WAIT_SECONDS.observe(0.8)
    TIMEOUTS_FIRED.labels(name="p2p.ping").inc()


def seed_trace(trace_id: str) -> None:
    """Spans continuing `trace_id` (what a cross-node request would
    leave here) plus a one-batch pipeline timeline carrying it."""
    from spacedrive_tpu import flight, tracing

    with tracing.continue_trace(f"{trace_id}-1"):
        with tracing.span("sync.pull", library="fleet-self-check"):
            with tracing.span("job.step", step=1):
                pass
    run = flight.new_run_token()
    t0 = time.perf_counter()
    rec = flight.RECORDER
    rec.record("stage", batch=1, t0=t0, t1=t0 + 0.004,
               trace=trace_id, run=run)
    rec.record("h2d", batch=1, t0=t0 + 0.004, t1=t0 + 0.007,
               device="0", trace=trace_id, run=run)
    rec.record("kernel", batch=1, t0=t0 + 0.007, t1=t0 + 0.008,
               device="0", trace=trace_id, run=run)
    rec.record("retire", batch=1, t0=t0 + 0.008, t1=t0 + 0.009,
               trace=trace_id, run=run)


async def serve(name: str, trace_id: str) -> None:
    from spacedrive_tpu.api.server import ApiServer
    from spacedrive_tpu.node import Node

    with tempfile.TemporaryDirectory() as td:
        # Name the node BEFORE boot: health snapshots capture identity
        # at construction.
        def write_config() -> None:
            with open(os.path.join(td, "node_state.sdconfig"),
                      "w") as f:
                json.dump({"version": 1, "id": uuid.uuid4().hex,
                           "name": name, "features": []}, f)
        await asyncio.to_thread(write_config)
        node = Node(td)
        await node.start()
        server = ApiServer(node)
        port = await server.start("127.0.0.1", 0)
        seed_saturations()
        if trace_id:
            seed_trace(trace_id)
        node.health.sample()
        print(json.dumps({"port": port, "id": node.config.id.hex(),
                          "name": node.config.name}), flush=True)
        # Park until the parent closes stdin (its teardown handle) —
        # read off-loop so the rspc host keeps serving.
        await asyncio.get_running_loop().run_in_executor(
            None, sys.stdin.read)
        await server.stop()
        await node.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet self-check peer (one obs-serving node)")
    ap.add_argument("--name", default="fleet-peer",
                    help="node name (the fleet row label)")
    ap.add_argument("--trace", default="",
                    help="hex trace id to seed spans/timeline under")
    args = ap.parse_args(argv)
    asyncio.run(serve(args.name, args.trace))
    return 0


if __name__ == "__main__":
    sys.exit(main())
