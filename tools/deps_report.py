"""Dependency/license report generator.

Equivalent of the reference's build tooling crate `deps-generator`
(/root/reference/crates/deps-generator/src/main.rs:13-25), which emits
the dependency + license inventory consumed by FOSSA/about pages. Here
the inventory comes from the live Python environment: every distribution
the `spacedrive_tpu` package imports (directly or transitively),
with version and license, as JSON on stdout.

    python tools/deps_report.py [--all]
"""

from __future__ import annotations

import json
import sys
from importlib import metadata

# The framework's direct import surface (kept by hand, checked by test).
DIRECT = [
    "jax", "jaxlib", "numpy", "msgpack", "aiohttp", "cryptography",
    "argon2-cffi", "pillow",
]


def _license_of(dist) -> str:
    meta = dist.metadata
    lic = meta.get("License-Expression") or meta.get("License") or ""
    if not lic or lic == "UNKNOWN" or len(lic) > 120:
        for c in meta.get_all("Classifier") or []:
            if c.startswith("License ::"):
                lic = c.split("::")[-1].strip()
                break
    return lic or "unknown"


def report(include_all: bool = False) -> list:
    names = (sorted({d.metadata["Name"] for d in metadata.distributions()
                     if d.metadata["Name"]})
             if include_all else DIRECT)
    out = []
    for name in names:
        try:
            dist = metadata.distribution(name)
        except metadata.PackageNotFoundError:
            out.append({"name": name, "version": None,
                        "license": "NOT INSTALLED"})
            continue
        out.append({
            "name": name,
            "version": dist.version,
            "license": _license_of(dist),
        })
    return out


if __name__ == "__main__":
    print(json.dumps(report("--all" in sys.argv), indent=2))
