// sdio: the native I/O + CPU-hash plane of the TPU-native VDFS engine.
//
// This is the C++ equivalent of the reference's Rust I/O layer — the role
// played by tokio::fs + the blake3 crate in
// /root/reference/core/src/object/cas.rs:23-62 (sampled CAS IDs) and
// /root/reference/core/src/object/validation/hash.rs:10-24 (full-file
// checksums). Instead of per-file async tasks, everything here is batched:
// a caller hands N paths and gets back dense payload grids (for the TPU
// backends) or finished digests (the fast CPU backend), computed by a
// pool of worker threads over pread(2).
//
// BLAKE3 is implemented from the public spec (same structure as the
// framework's Python oracle spacedrive_tpu/ops/blake3_ref.py); hash mode
// only. Exports use a plain C ABI for ctypes.
//
// Build: `make -C native` → build/libsdio.so.

#include <algorithm>
#include <array>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// BLAKE3 (hash mode), from the public spec.
// ---------------------------------------------------------------------------

constexpr uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

constexpr int MSG_PERMUTATION[16] = {2, 6,  3, 10, 7, 0,  4,  13,
                                     1, 11, 12, 5, 9, 14, 15, 8};

constexpr uint32_t CHUNK_START = 1u << 0;
constexpr uint32_t CHUNK_END = 1u << 1;
constexpr uint32_t PARENT = 1u << 2;
constexpr uint32_t ROOT = 1u << 3;

constexpr size_t BLOCK_LEN = 64;
constexpr size_t CHUNK_LEN = 1024;

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

#define G(a, b, c, d, mx, my)      \
  do {                             \
    a = a + b + (mx);              \
    d = rotr32(d ^ a, 16);         \
    c = c + d;                     \
    b = rotr32(b ^ c, 12);         \
    a = a + b + (my);              \
    d = rotr32(d ^ a, 8);          \
    c = c + d;                     \
    b = rotr32(b ^ c, 7);          \
  } while (0)

// One compression. out16 is the full 16-word output state; words 0..8 are
// the new chaining value.
static void compress(const uint32_t cv[8], const uint32_t block[16],
                     uint64_t counter, uint32_t block_len, uint32_t flags,
                     uint32_t out16[16]) {
  uint32_t s0 = cv[0], s1 = cv[1], s2 = cv[2], s3 = cv[3];
  uint32_t s4 = cv[4], s5 = cv[5], s6 = cv[6], s7 = cv[7];
  uint32_t s8 = IV[0], s9 = IV[1], s10 = IV[2], s11 = IV[3];
  uint32_t s12 = (uint32_t)counter;
  uint32_t s13 = (uint32_t)(counter >> 32);
  uint32_t s14 = block_len;
  uint32_t s15 = flags;

  uint32_t m[16];
  std::memcpy(m, block, sizeof(m));

  for (int r = 0; r < 7; r++) {
    G(s0, s4, s8, s12, m[0], m[1]);
    G(s1, s5, s9, s13, m[2], m[3]);
    G(s2, s6, s10, s14, m[4], m[5]);
    G(s3, s7, s11, s15, m[6], m[7]);
    G(s0, s5, s10, s15, m[8], m[9]);
    G(s1, s6, s11, s12, m[10], m[11]);
    G(s2, s7, s8, s13, m[12], m[13]);
    G(s3, s4, s9, s14, m[14], m[15]);
    if (r < 6) {
      uint32_t p[16];
      for (int i = 0; i < 16; i++) p[i] = m[MSG_PERMUTATION[i]];
      std::memcpy(m, p, sizeof(m));
    }
  }

  out16[0] = s0 ^ s8;
  out16[1] = s1 ^ s9;
  out16[2] = s2 ^ s10;
  out16[3] = s3 ^ s11;
  out16[4] = s4 ^ s12;
  out16[5] = s5 ^ s13;
  out16[6] = s6 ^ s14;
  out16[7] = s7 ^ s15;
  out16[8] = s8 ^ cv[0];
  out16[9] = s9 ^ cv[1];
  out16[10] = s10 ^ cv[2];
  out16[11] = s11 ^ cv[3];
  out16[12] = s12 ^ cv[4];
  out16[13] = s13 ^ cv[5];
  out16[14] = s14 ^ cv[6];
  out16[15] = s15 ^ cv[7];
}

static void words_of_block(const uint8_t* data, size_t len, uint32_t w[16]) {
  uint8_t block[BLOCK_LEN] = {0};
  std::memcpy(block, data, len);
  for (int i = 0; i < 16; i++) {
    w[i] = (uint32_t)block[4 * i] | ((uint32_t)block[4 * i + 1] << 8) |
           ((uint32_t)block[4 * i + 2] << 16) |
           ((uint32_t)block[4 * i + 3] << 24);
  }
}

static void le64(uint64_t v, uint8_t out[8]) {
  for (int i = 0; i < 8; i++) out[i] = (uint8_t)(v >> (8 * i));
}

// Message-word schedule: SCHED[r][i] is the index into the original block
// of the word used at position i of round r (the permutation applied r
// times), so rounds can index the message directly instead of
// re-permuting 16 vectors between rounds.
struct Sched {
  uint8_t v[7][16];
};
static constexpr Sched make_sched() {
  Sched s{};
  for (int i = 0; i < 16; i++) s.v[0][i] = (uint8_t)i;
  for (int r = 1; r < 7; r++)
    for (int i = 0; i < 16; i++) s.v[r][i] = s.v[r - 1][MSG_PERMUTATION[i]];
  return s;
}
static constexpr Sched SCHED = make_sched();

#if defined(__AVX2__)
// ---------------------------------------------------------------------------
// 8-lane SIMD BLAKE3: one 32-bit state word per __m256i, eight independent
// compressions per instruction. Used two ways:
//   - hash8_leaf_cvs: 8 consecutive chunks of ONE stream (the streaming
//     hasher's fast path — checksums, small-file CAS);
//   - blake3_x8: 8 equal-length messages in lockstep, tree and all (the
//     batched CAS grid, where every large-file message is 57,352 bytes).
// ---------------------------------------------------------------------------
#include <immintrin.h>

namespace wide {

static inline __m256i rotr_v(__m256i x, int n) {
#if defined(__AVX512VL__)
  return _mm256_ror_epi32(x, n);
#else
  return _mm256_or_si256(_mm256_srli_epi32(x, n),
                         _mm256_slli_epi32(x, 32 - n));
#endif
}

#define GV(a, b, c, d, mx, my)                           \
  do {                                                   \
    a = _mm256_add_epi32(_mm256_add_epi32(a, b), (mx));  \
    d = rotr_v(_mm256_xor_si256(d, a), 16);              \
    c = _mm256_add_epi32(c, d);                          \
    b = rotr_v(_mm256_xor_si256(b, c), 12);              \
    a = _mm256_add_epi32(_mm256_add_epi32(a, b), (my));  \
    d = rotr_v(_mm256_xor_si256(d, a), 8);               \
    c = _mm256_add_epi32(c, d);                          \
    b = rotr_v(_mm256_xor_si256(b, c), 7);               \
  } while (0)

// In-place 8x8 transpose of 32-bit elements (v[r] = row r).
static inline void transpose8(__m256i v[8]) {
  __m256i t0 = _mm256_unpacklo_epi32(v[0], v[1]);
  __m256i t1 = _mm256_unpackhi_epi32(v[0], v[1]);
  __m256i t2 = _mm256_unpacklo_epi32(v[2], v[3]);
  __m256i t3 = _mm256_unpackhi_epi32(v[2], v[3]);
  __m256i t4 = _mm256_unpacklo_epi32(v[4], v[5]);
  __m256i t5 = _mm256_unpackhi_epi32(v[4], v[5]);
  __m256i t6 = _mm256_unpacklo_epi32(v[6], v[7]);
  __m256i t7 = _mm256_unpackhi_epi32(v[6], v[7]);
  __m256i u0 = _mm256_unpacklo_epi64(t0, t2);
  __m256i u1 = _mm256_unpackhi_epi64(t0, t2);
  __m256i u2 = _mm256_unpacklo_epi64(t1, t3);
  __m256i u3 = _mm256_unpackhi_epi64(t1, t3);
  __m256i u4 = _mm256_unpacklo_epi64(t4, t6);
  __m256i u5 = _mm256_unpackhi_epi64(t4, t6);
  __m256i u6 = _mm256_unpacklo_epi64(t5, t7);
  __m256i u7 = _mm256_unpackhi_epi64(t5, t7);
  v[0] = _mm256_permute2x128_si256(u0, u4, 0x20);
  v[1] = _mm256_permute2x128_si256(u1, u5, 0x20);
  v[2] = _mm256_permute2x128_si256(u2, u6, 0x20);
  v[3] = _mm256_permute2x128_si256(u3, u7, 0x20);
  v[4] = _mm256_permute2x128_si256(u0, u4, 0x31);
  v[5] = _mm256_permute2x128_si256(u1, u5, 0x31);
  v[6] = _mm256_permute2x128_si256(u2, u6, 0x31);
  v[7] = _mm256_permute2x128_si256(u3, u7, 0x31);
}

// Load one 64-byte block from each of 8 lanes, transposed into message
// vectors m[w] = word w across lanes (x86 is little-endian, so a plain
// 32-bit load IS the LE word decode).
static inline void load_block8(const uint8_t* const p[8], __m256i m[16]) {
  __m256i lo[8], hi[8];
  for (int j = 0; j < 8; j++) {
    lo[j] = _mm256_loadu_si256((const __m256i*)(const void*)p[j]);
    hi[j] = _mm256_loadu_si256((const __m256i*)(const void*)(p[j] + 32));
  }
  transpose8(lo);
  transpose8(hi);
  for (int w = 0; w < 8; w++) {
    m[w] = lo[w];
    m[8 + w] = hi[w];
  }
}

// Eight compressions at once; cv[w] is chaining-value word w across the
// lanes and is replaced with the new chaining value (low-half output).
static void compress8_cv(__m256i cv[8], const __m256i m[16], __m256i ctr_lo,
                         __m256i ctr_hi, uint32_t block_len, uint32_t flags) {
  __m256i s0 = cv[0], s1 = cv[1], s2 = cv[2], s3 = cv[3];
  __m256i s4 = cv[4], s5 = cv[5], s6 = cv[6], s7 = cv[7];
  __m256i s8 = _mm256_set1_epi32((int32_t)IV[0]);
  __m256i s9 = _mm256_set1_epi32((int32_t)IV[1]);
  __m256i s10 = _mm256_set1_epi32((int32_t)IV[2]);
  __m256i s11 = _mm256_set1_epi32((int32_t)IV[3]);
  __m256i s12 = ctr_lo;
  __m256i s13 = ctr_hi;
  __m256i s14 = _mm256_set1_epi32((int32_t)block_len);
  __m256i s15 = _mm256_set1_epi32((int32_t)flags);

  for (int r = 0; r < 7; r++) {
    const uint8_t* sc = SCHED.v[r];
    GV(s0, s4, s8, s12, m[sc[0]], m[sc[1]]);
    GV(s1, s5, s9, s13, m[sc[2]], m[sc[3]]);
    GV(s2, s6, s10, s14, m[sc[4]], m[sc[5]]);
    GV(s3, s7, s11, s15, m[sc[6]], m[sc[7]]);
    GV(s0, s5, s10, s15, m[sc[8]], m[sc[9]]);
    GV(s1, s6, s11, s12, m[sc[10]], m[sc[11]]);
    GV(s2, s7, s8, s13, m[sc[12]], m[sc[13]]);
    GV(s3, s4, s9, s14, m[sc[14]], m[sc[15]]);
  }

  cv[0] = _mm256_xor_si256(s0, s8);
  cv[1] = _mm256_xor_si256(s1, s9);
  cv[2] = _mm256_xor_si256(s2, s10);
  cv[3] = _mm256_xor_si256(s3, s11);
  cv[4] = _mm256_xor_si256(s4, s12);
  cv[5] = _mm256_xor_si256(s5, s13);
  cv[6] = _mm256_xor_si256(s6, s14);
  cv[7] = _mm256_xor_si256(s7, s15);
}

// Leaf CVs of 8 FULL chunks gathered from ARBITRARY lanes: lane j hashes
// the 1024 bytes at ptrs[j] with chunk counter counters[j]. The caller
// guarantees every lane is a full non-root leaf (part of a multi-chunk
// message) — the flag schedule (CHUNK_START on block 0, CHUNK_END on
// block 15, never ROOT) is then identical across lanes, so chunks from
// DIFFERENT messages can share one SIMD dispatch. This is what lets
// ~4 KiB files (4 full chunks each) still fill all 8 lanes: pool the
// chunks across a group of files instead of within one stream.
static void hash8_leaf_cvs_gather(const uint8_t* const ptrs[8],
                                  const uint64_t counters[8],
                                  uint32_t out_cvs[8][8]) {
  __m256i cv[8];
  for (int i = 0; i < 8; i++) cv[i] = _mm256_set1_epi32((int32_t)IV[i]);
  alignas(32) uint32_t clo[8], chi[8];
  for (int j = 0; j < 8; j++) {
    clo[j] = (uint32_t)counters[j];
    chi[j] = (uint32_t)(counters[j] >> 32);
  }
  __m256i ctr_lo = _mm256_load_si256((const __m256i*)clo);
  __m256i ctr_hi = _mm256_load_si256((const __m256i*)chi);

  const uint8_t* p[8];
  for (int j = 0; j < 8; j++) p[j] = ptrs[j];
  for (int b = 0; b < 16; b++) {
    __m256i m[16];
    load_block8(p, m);
    uint32_t flags =
        (b == 0 ? CHUNK_START : 0u) | (b == 15 ? CHUNK_END : 0u);
    compress8_cv(cv, m, ctr_lo, ctr_hi, BLOCK_LEN, flags);
    for (int j = 0; j < 8; j++) p[j] += BLOCK_LEN;
  }
  transpose8(cv);  // word-across-lane -> lane rows
  for (int j = 0; j < 8; j++)
    _mm256_storeu_si256((__m256i*)(void*)out_cvs[j], cv[j]);
}

// Leaf CVs of 8 consecutive FULL chunks of one stream: lane j hashes
// data[j*1024 .. j*1024+1024) with chunk counter counter0+j. The caller
// guarantees none of them is the final chunk.
static void hash8_leaf_cvs(const uint8_t* data, uint64_t counter0,
                           uint32_t out_cvs[8][8]) {
  const uint8_t* p[8];
  uint64_t c[8];
  for (int j = 0; j < 8; j++) {
    p[j] = data + (size_t)j * CHUNK_LEN;
    c[j] = counter0 + (uint64_t)j;
  }
  hash8_leaf_cvs_gather(p, c, out_cvs);
}

// Chaining values of 8 lanes, one word per vector.
struct CVv {
  __m256i w[8];
};

static inline void merge_parent_v(const CVv& l, const CVv& r, uint32_t flags,
                                  CVv* out) {
  __m256i m[16];
  for (int i = 0; i < 8; i++) {
    m[i] = l.w[i];
    m[8 + i] = r.w[i];
  }
  CVv cv;
  for (int i = 0; i < 8; i++) cv.w[i] = _mm256_set1_epi32((int32_t)IV[i]);
  compress8_cv(cv.w, m, _mm256_setzero_si256(), _mm256_setzero_si256(),
               BLOCK_LEN, flags);
  *out = cv;
}

// Hash 8 equal-length messages in lockstep — identical tree shape, so
// leaves, parents and root are all 8-wide with no shuffling between
// stages. Message j is (optional 8-byte LE prefixes[j]) ‖ rows[j];
// total_len includes the prefix. Digests are 32 bytes per lane.
static void blake3_x8(const uint8_t* const rows[8], uint64_t total_len,
                      const uint64_t* prefixes, uint8_t* digests,
                      int64_t digest_stride) {
  const uint64_t pre = prefixes ? 8 : 0;
  const uint64_t n_chunks =
      total_len == 0 ? 1 : (total_len + CHUNK_LEN - 1) / CHUNK_LEN;
  CVv stack[64];
  int sp = 0;
  alignas(32) uint8_t stage[8][BLOCK_LEN];
  CVv cv;

  for (uint64_t c = 0; c < n_chunks; c++) {
    const uint64_t chunk_off = c * CHUNK_LEN;
    const uint64_t chunk_len =
        total_len == 0
            ? 0
            : std::min<uint64_t>(CHUNK_LEN, total_len - chunk_off);
    const int n_blocks =
        chunk_len == 0 ? 1 : (int)((chunk_len + BLOCK_LEN - 1) / BLOCK_LEN);
    for (int i = 0; i < 8; i++) cv.w[i] = _mm256_set1_epi32((int32_t)IV[i]);
    __m256i ctr_lo = _mm256_set1_epi32((int32_t)(uint32_t)c);
    __m256i ctr_hi = _mm256_set1_epi32((int32_t)(uint32_t)(c >> 32));

    for (int b = 0; b < n_blocks; b++) {
      const uint64_t bo = chunk_off + (uint64_t)b * BLOCK_LEN;
      const uint32_t blen =
          (uint32_t)std::min<uint64_t>(BLOCK_LEN, chunk_len - (uint64_t)b * BLOCK_LEN);
      __m256i m[16];
      if (blen == BLOCK_LEN && bo >= pre) {
        const uint8_t* p[8];
        for (int j = 0; j < 8; j++) p[j] = rows[j] + (bo - pre);
        load_block8(p, m);
      } else {
        for (int j = 0; j < 8; j++) {
          std::memset(stage[j], 0, BLOCK_LEN);
          uint64_t o = bo;
          uint32_t k = 0;
          if (o < pre) {
            uint8_t p8[8];
            le64(prefixes[j], p8);
            while (o < pre && k < blen) stage[j][k++] = p8[o++];
          }
          if (k < blen)
            std::memcpy(stage[j] + k, rows[j] + (o - pre), blen - k);
        }
        const uint8_t* p[8] = {stage[0], stage[1], stage[2], stage[3],
                               stage[4], stage[5], stage[6], stage[7]};
        load_block8(p, m);
      }
      uint32_t flags = (b == 0 ? CHUNK_START : 0u) |
                       (b == n_blocks - 1 ? CHUNK_END : 0u);
      if (n_chunks == 1 && b == n_blocks - 1) flags |= ROOT;
      compress8_cv(cv.w, m, ctr_lo, ctr_hi, blen, flags);
    }

    if (n_chunks == 1) break;
    if (c < n_chunks - 1) {
      uint64_t total = c + 1;
      while ((total & 1) == 0) {
        merge_parent_v(stack[--sp], cv, PARENT, &cv);
        total >>= 1;
      }
      stack[sp++] = cv;
    } else {
      while (sp > 1) merge_parent_v(stack[--sp], cv, PARENT, &cv);
      merge_parent_v(stack[0], cv, PARENT | ROOT, &cv);
    }
  }

  __m256i out[8];
  for (int i = 0; i < 8; i++) out[i] = cv.w[i];
  transpose8(out);
  for (int j = 0; j < 8; j++)
    _mm256_storeu_si256((__m256i*)(void*)(digests + j * digest_stride),
                        out[j]);
}

}  // namespace wide
#endif  // __AVX2__

// One parent-node compression: block = left CV ‖ right CV, IV state.
// Shared by the streaming hasher and the batched small-file tree fold —
// any change here changes every digest the plane produces.
static void merge_parent_cv(const uint32_t left[8], const uint32_t right[8],
                            uint32_t flags, uint32_t cv_out[8]) {
  uint32_t block[16], out[16];
  std::memcpy(block, left, 8 * sizeof(uint32_t));
  std::memcpy(block + 8, right, 8 * sizeof(uint32_t));
  compress(IV, block, 0, BLOCK_LEN, flags, out);
  std::memcpy(cv_out, out, 8 * sizeof(uint32_t));
}

static void store_digest_le(const uint32_t out16[16], uint8_t out[32]) {
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)out16[i];
    out[4 * i + 1] = (uint8_t)(out16[i] >> 8);
    out[4 * i + 2] = (uint8_t)(out16[i] >> 16);
    out[4 * i + 3] = (uint8_t)(out16[i] >> 24);
  }
}

// Streaming hasher — same state machine as the Python oracle: a chunk
// state plus a binary-counter CV stack of completed subtrees.
class Blake3 {
 public:
  Blake3() { reset(); }

  void reset() {
    std::memcpy(chunk_cv_, IV, sizeof(chunk_cv_));
    chunk_counter_ = 0;
    buf_len_ = 0;
    blocks_compressed_ = 0;
    stack_.clear();
  }

  void update(const uint8_t* data, size_t len) {
    while (len > 0) {
      if (chunk_length() == CHUNK_LEN) {
        // Chunk complete with more input following: finalize as a
        // non-root leaf, fold the stack like a binary counter.
        uint32_t cv[8];
        chunk_output(0, cv);
        push_chunk_cv(cv);
      }
#if defined(__AVX2__)
      // At a chunk boundary with strictly more than 8 chunks left, 8
      // full chunks complete here and none can be the final one: hash
      // them 8-wide and fold their CVs through the same stack.
      while (chunk_length() == 0 && len > 8 * CHUNK_LEN) {
        uint32_t cvs[8][8];
        wide::hash8_leaf_cvs(data, chunk_counter_, cvs);
        for (int j = 0; j < 8; j++) push_chunk_cv(cvs[j]);
        data += 8 * CHUNK_LEN;
        len -= 8 * CHUNK_LEN;
      }
#endif
      // Absorb into the current chunk. Only compress a buffered block
      // once more input exists, so CHUNK_END stays available.
      if (buf_len_ == BLOCK_LEN) {
        uint32_t w[16], out[16];
        words_of_block(buf_, BLOCK_LEN, w);
        compress(chunk_cv_, w, chunk_counter_, BLOCK_LEN, start_flag(), out);
        std::memcpy(chunk_cv_, out, 8 * sizeof(uint32_t));
        blocks_compressed_++;
        buf_len_ = 0;
      }
      size_t want = BLOCK_LEN - buf_len_;
      size_t room = CHUNK_LEN - chunk_length();
      size_t take = len < want ? len : want;
      if (take > room) take = room;
      std::memcpy(buf_ + buf_len_, data, take);
      buf_len_ += take;
      data += take;
      len -= take;
    }
  }

  void finalize(uint8_t out[32]) {
    uint32_t out16[16];
    if (stack_.empty()) {
      uint32_t w[16];
      words_of_block(buf_, buf_len_, w);
      compress(chunk_cv_, w, chunk_counter_, (uint32_t)buf_len_,
               start_flag() | CHUNK_END | ROOT, out16);
    } else {
      uint32_t cv[8];
      chunk_output(0, cv);
      for (size_t i = stack_.size() - 1; i > 0; i--) {
        merge_parent_cv(stack_[i].data(), cv, PARENT, cv);
      }
      uint32_t parent_block[16];
      std::memcpy(parent_block, stack_[0].data(), 8 * sizeof(uint32_t));
      std::memcpy(parent_block + 8, cv, 8 * sizeof(uint32_t));
      compress(IV, parent_block, 0, BLOCK_LEN, PARENT | ROOT, out16);
    }
    store_digest_le(out16, out);
  }

 private:
  // Fold a completed (non-final) chunk's CV into the subtree stack like
  // a binary counter, then reset the chunk state for the next chunk.
  void push_chunk_cv(const uint32_t cv_in[8]) {
    uint32_t cv[8];
    std::memcpy(cv, cv_in, sizeof(cv));
    uint64_t total = chunk_counter_ + 1;
    while ((total & 1) == 0) {
      merge_parent_cv(stack_.back().data(), cv, PARENT, cv);
      stack_.pop_back();
      total >>= 1;
    }
    std::array<uint32_t, 8> entry;
    std::memcpy(entry.data(), cv, sizeof(cv));
    stack_.push_back(entry);
    chunk_counter_++;
    std::memcpy(chunk_cv_, IV, sizeof(chunk_cv_));
    buf_len_ = 0;
    blocks_compressed_ = 0;
  }

  size_t chunk_length() const {
    return blocks_compressed_ * BLOCK_LEN + buf_len_;
  }
  uint32_t start_flag() const {
    return blocks_compressed_ == 0 ? CHUNK_START : 0;
  }
  void chunk_output(uint32_t extra_flags, uint32_t cv_out[8]) {
    uint32_t w[16], out[16];
    words_of_block(buf_, buf_len_, w);
    compress(chunk_cv_, w, chunk_counter_, (uint32_t)buf_len_,
             start_flag() | CHUNK_END | extra_flags, out);
    std::memcpy(cv_out, out, 8 * sizeof(uint32_t));
  }
  uint32_t chunk_cv_[8];
  uint64_t chunk_counter_;
  uint8_t buf_[BLOCK_LEN];
  size_t buf_len_;
  size_t blocks_compressed_;
  std::vector<std::array<uint32_t, 8>> stack_;
};

// ---------------------------------------------------------------------------
// Chunk-level scalar helpers for the cross-file batched small hasher:
// leaf CVs and tree merges over PRE-COMPUTED chunk CVs, byte-identical
// to streaming the same message through Blake3 above. The SIMD gather
// kernel produces full-chunk CVs; these cover tails, single-chunk roots
// and the per-message parent tree.
// ---------------------------------------------------------------------------

// CV of one NON-ROOT leaf chunk (1..1024 bytes of a multi-chunk message).
static void leaf_chunk_cv(const uint8_t* data, size_t len, uint64_t counter,
                          uint32_t out_cv[8]) {
  uint32_t cv[8];
  std::memcpy(cv, IV, sizeof(cv));
  const size_t n_blocks = (len + BLOCK_LEN - 1) / BLOCK_LEN;
  uint32_t out16[16];
  for (size_t b = 0; b < n_blocks; b++) {
    const size_t blen = std::min(BLOCK_LEN, len - b * BLOCK_LEN);
    uint32_t w[16];
    words_of_block(data + b * BLOCK_LEN, blen, w);
    const uint32_t flags = (b == 0 ? CHUNK_START : 0u) |
                           (b == n_blocks - 1 ? CHUNK_END : 0u);
    compress(cv, w, counter, (uint32_t)blen, flags, out16);
    std::memcpy(cv, out16, 8 * sizeof(uint32_t));
  }
  std::memcpy(out_cv, cv, 8 * sizeof(uint32_t));
}

// Root digest of a message that fits in ONE chunk (0..1024 bytes).
static void single_chunk_root(const uint8_t* msg, size_t len,
                              uint8_t out[32]) {
  uint32_t cv[8];
  std::memcpy(cv, IV, sizeof(cv));
  const size_t n_blocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  uint32_t out16[16];
  for (size_t b = 0; b < n_blocks; b++) {
    const size_t blen = len == 0 ? 0 : std::min(BLOCK_LEN, len - b * BLOCK_LEN);
    uint32_t w[16];
    words_of_block(msg + b * BLOCK_LEN, blen, w);
    uint32_t flags = (b == 0 ? CHUNK_START : 0u);
    if (b == n_blocks - 1) flags |= CHUNK_END | ROOT;
    compress(cv, w, 0, (uint32_t)blen, flags, out16);
    std::memcpy(cv, out16, 8 * sizeof(uint32_t));
  }
  store_digest_le(out16, out);
}

// Root digest from n >= 2 in-order leaf CVs: the same binary-counter
// stack fold as Blake3::push_chunk_cv/finalize, over precomputed CVs.
static void merge_cvs_root(const uint32_t (*cvs)[8], uint64_t n,
                           uint8_t out[32]) {
  uint32_t stack[64][8];
  int sp = 0;
  for (uint64_t c = 0; c + 1 < n; c++) {
    uint32_t cv[8];
    std::memcpy(cv, cvs[c], sizeof(cv));
    uint64_t total = c + 1;
    while ((total & 1) == 0) {
      merge_parent_cv(stack[--sp], cv, PARENT, cv);
      total >>= 1;
    }
    std::memcpy(stack[sp++], cv, sizeof(cv));
  }
  uint32_t cv[8];
  std::memcpy(cv, cvs[n - 1], sizeof(cv));
  for (int i = sp - 1; i > 0; i--) merge_parent_cv(stack[i], cv, PARENT, cv);
  uint32_t parent_block[16], out16[16];
  std::memcpy(parent_block, stack[0], 8 * sizeof(uint32_t));
  std::memcpy(parent_block + 8, cv, 8 * sizeof(uint32_t));
  compress(IV, parent_block, 0, BLOCK_LEN, PARENT | ROOT, out16);
  store_digest_le(out16, out);
}

// ---------------------------------------------------------------------------
// CAS sampling layout (core/src/object/cas.rs:10-15,23-62 semantics).
// ---------------------------------------------------------------------------

constexpr uint64_t SAMPLE_COUNT = 4;
constexpr uint64_t SAMPLE_SIZE = 1024 * 10;
constexpr uint64_t HEADER_OR_FOOTER_SIZE = 1024 * 8;
constexpr uint64_t MINIMUM_FILE_SIZE = 1024 * 100;
// The batched whole-file hasher caps at the CAS small-class edge:
// sd_cas_digests partitions by MINIMUM_FILE_SIZE and relies on every
// partitioned lane fitting the group buffer.
constexpr uint64_t SMALL_WHOLE_CAP = MINIMUM_FILE_SIZE;
constexpr uint64_t LARGE_PAYLOAD =
    2 * HEADER_OR_FOOTER_SIZE + SAMPLE_COUNT * SAMPLE_SIZE;  // 57344
constexpr size_t CHECKSUM_BLOCK = 1 << 20;  // validation/hash.rs:8

// Status codes shared with the ctypes wrapper.
enum Status : int32_t {
  OK = 0,
  ERR_OPEN = -1,
  ERR_SHORT_READ = -2,
  ERR_GREW = -3,   // small file larger than its declared class
  ERR_EMPTY = -4,  // empty file: no CAS ID (mod.rs:86)
  ERR_IO = -5,
};

static bool pread_full(int fd, uint8_t* dst, size_t len, uint64_t off) {
  size_t done = 0;
  while (done < len) {
    ssize_t r = pread(fd, dst + done, len - done, (off_t)(off + done));
    if (r <= 0) return false;
    done += (size_t)r;
  }
  return true;
}

// Sampled read for a large (> 100 KiB) file into a 57,344-byte row.
// Header/sample offsets come from the declared size; the footer reads
// relative to the file's real end (SeekFrom::End(-8192) in cas.rs:57).
static int32_t read_sampled(int fd, uint64_t size, uint8_t* out) {
  uint64_t jump = (size - 2 * HEADER_OR_FOOTER_SIZE) / SAMPLE_COUNT;
  uint8_t* pos = out;
  if (!pread_full(fd, pos, HEADER_OR_FOOTER_SIZE, 0)) return ERR_SHORT_READ;
  pos += HEADER_OR_FOOTER_SIZE;
  for (uint64_t k = 0; k < SAMPLE_COUNT; k++) {
    if (!pread_full(fd, pos, SAMPLE_SIZE, HEADER_OR_FOOTER_SIZE + k * jump))
      return ERR_SHORT_READ;
    pos += SAMPLE_SIZE;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < HEADER_OR_FOOTER_SIZE)
    return ERR_SHORT_READ;
  if (!pread_full(fd, pos, HEADER_OR_FOOTER_SIZE,
                  (uint64_t)st.st_size - HEADER_OR_FOOTER_SIZE))
    return ERR_SHORT_READ;
  return OK;
}

// Whole-file read for a small (≤ cap) file; flags files that grew.
static int32_t read_small(int fd, uint64_t cap, uint8_t* out,
                          int32_t* out_len) {
  size_t done = 0;
  for (;;) {
    ssize_t r = pread(fd, out + done, cap + 1 - done, (off_t)done);
    if (r < 0) return ERR_IO;
    if (r == 0) break;
    done += (size_t)r;
    if (done > cap) return ERR_GREW;
  }
  *out_len = (int32_t)done;
  return OK;
}

// Sampled read for a large file via one shared read-only mapping: six
// region memcpys out of the page cache instead of six preads. Offsets
// come from the DECLARED size (cas.rs:43 parity — a stale index entry
// must sample the same offsets the oracle would); every region is
// bounds-checked against the file's real length so a file truncated
// between index and stage degrades to ERR_SHORT_READ exactly like the
// pread path. mmap failure (exotic filesystems, /proc files) degrades
// to read_sampled, which reads the same bytes. A truncate racing the
// memcpy itself can SIGBUS like any mapped reader — the same window
// the reference's mmap-less path shrinks but does not close; callers
// that cannot tolerate it stage through sd_stage_large instead.
static const uint64_t MMAP_THRESHOLD = 8ull << 20;  // 8 MiB

static int32_t read_sampled_mmap(int fd, uint64_t declared, uint8_t* out) {
  struct stat st;
  if (fstat(fd, &st) != 0) return ERR_IO;
  const uint64_t real = (uint64_t)st.st_size;
  if (real < HEADER_OR_FOOTER_SIZE) return ERR_SHORT_READ;
  // Below the threshold six preads beat a mapping: mmap + munmap cost
  // two syscalls plus a cross-thread TLB shootdown per file, which
  // dominates for ~100 KB files staged by the thousands. Past it the
  // shared mapping wins (one setup amortized over sparse regions).
  if (real < MMAP_THRESHOLD) return read_sampled(fd, declared, out);
  void* m = mmap(nullptr, (size_t)real, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) return read_sampled(fd, declared, out);
  const uint8_t* base = (const uint8_t*)m;
  const uint64_t jump = (declared - 2 * HEADER_OR_FOOTER_SIZE) / SAMPLE_COUNT;
  int32_t rc = OK;
  uint8_t* pos = out;
  std::memcpy(pos, base, HEADER_OR_FOOTER_SIZE);
  pos += HEADER_OR_FOOTER_SIZE;
  for (uint64_t k = 0; k < SAMPLE_COUNT; k++) {
    const uint64_t off = HEADER_OR_FOOTER_SIZE + k * jump;
    if (off + SAMPLE_SIZE > real) {
      rc = ERR_SHORT_READ;
      break;
    }
    std::memcpy(pos, base + off, SAMPLE_SIZE);
    pos += SAMPLE_SIZE;
  }
  if (rc == OK)
    std::memcpy(pos, base + (real - HEADER_OR_FOOTER_SIZE),
                HEADER_OR_FOOTER_SIZE);
  munmap(m, (size_t)real);
  return rc;
}

// Whole-file read for a small file, preadv straight into the packed
// row (the destination must have cap+1 bytes: the extra byte is the
// grew-past-class detector, landing in the row's zero padding).
static int32_t read_small_v(int fd, uint64_t cap, uint8_t* out,
                            int32_t* out_len) {
  size_t done = 0;
  for (;;) {
    struct iovec iov = {out + done, (size_t)(cap + 1 - done)};
    ssize_t r = preadv(fd, &iov, 1, (off_t)done);
    if (r < 0) {
      if (errno == EINTR) continue;
      return ERR_IO;
    }
    if (r == 0) break;
    done += (size_t)r;
    if (done > cap) return ERR_GREW;
  }
  *out_len = (int32_t)done;
  return OK;
}

// Simple work-stealing-free parallel for: N items, an atomic cursor,
// hardware_concurrency workers (the batched replacement for the
// reference's join_all of ≤100 async tasks, file_identifier/mod.rs:107).
template <typename F>
static void parallel_for(int64_t n, int n_threads, F&& fn) {
  if (n <= 0) return;
  int hw = (int)std::thread::hardware_concurrency();
  if (hw <= 0) hw = 4;
  if (n_threads <= 0) n_threads = hw;
  if ((int64_t)n_threads > n) n_threads = (int)n;
  if (n_threads == 1) {
    for (int64_t i = 0; i < n; i++) fn(i);
    return;
  }
  std::atomic<int64_t> cursor{0};
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int t = 0; t < n_threads; t++) {
    workers.emplace_back([&]() {
      for (;;) {
        int64_t i = cursor.fetch_add(1);
        if (i >= n) return;
        fn(i);
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

#if defined(__AVX2__)
// Whole-file hashing for small files, batched 8 per group with their
// full 1024-byte chunks POOLED ACROSS the group via the gather kernel:
// a ~4 KiB file has only 4 full chunks, far short of the 8 consecutive
// chunks the within-stream fast path needs, but 8 such files together
// keep all SIMD lanes busy. Tails, single-chunk messages and parent
// merges stay scalar (~6% of the compressions). Message is [8-byte LE
// prefix_sizes[i] when non-null] ‖ whole ACTUAL content. Error lanes
// set status+done alone; lanes past SMALL_WHOLE_CAP leave done=0 for
// the caller's unbounded fallback. Shared by CAS IDs (declared-size
// prefix, cas.rs:23-27) and full-file checksums (no prefix).
static void hash_small_whole_groups(const std::vector<int64_t>& small,
                                    const char** paths,
                                    const uint64_t* prefix_sizes,
                                    uint8_t* digests, int32_t* status,
                                    std::vector<uint8_t>& done,
                                    int n_threads) {
  constexpr uint64_t MSG_CAP = 8 + SMALL_WHOLE_CAP;  // prefix + content
  constexpr uint32_t MAX_CVS = (uint32_t)(MSG_CAP / CHUNK_LEN) + 1;
  const uint64_t pre = prefix_sizes ? 8 : 0;
  const int64_t n_sgroups = (int64_t)small.size() / 8;
  parallel_for(n_sgroups, n_threads, [&](int64_t g) {
    // One zero-fill per WORKER THREAD, reused across its groups — a
    // fresh 819 KB vector per 8 tiny files would cost more in mmap +
    // memset than the hashing it feeds.
    thread_local std::vector<uint8_t> buf;
    if (buf.size() < (size_t)8 * (MSG_CAP + 1))
      buf.resize((size_t)8 * (MSG_CAP + 1));
    uint64_t mlen[8];
    bool live[8];
    for (int j = 0; j < 8; j++) {
      const int64_t i = small[(size_t)(g * 8 + j)];
      uint8_t* msg = buf.data() + (size_t)j * (MSG_CAP + 1);
      live[j] = false;
      mlen[j] = 0;
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) {
        status[i] = ERR_OPEN;
        done[(size_t)i] = 1;
        continue;
      }
      if (pre) le64(prefix_sizes[i], msg);
      // Whole ACTUAL file regardless of any declared size; read_small's
      // +1-byte headroom flags a file that grew past the cap, which
      // falls through to the caller's unbounded path (done stays 0).
      int32_t content_len = 0;
      const int32_t rs =
          read_small(fd, SMALL_WHOLE_CAP, msg + pre, &content_len);
      close(fd);
      if (rs == ERR_GREW) continue;
      if (rs != OK) {
        status[i] = rs;
        done[(size_t)i] = 1;
        continue;
      }
      mlen[j] = pre + (uint64_t)content_len;
      live[j] = true;
      done[(size_t)i] = 1;
    }

    // Pool every full leaf chunk of the group's multi-chunk messages.
    // A full FINAL chunk of a multi-chunk message is flag-identical to
    // any other full leaf (ROOT lives on the parent), so it pools too.
    struct Desc {
      const uint8_t* p;
      uint64_t ctr;
      uint8_t lane;
      uint8_t ci;
    };
    Desc ds[8 * (MSG_CAP / CHUNK_LEN)];
    int nd = 0;
    static_assert(MAX_CVS <= 256, "ci is uint8_t");
    uint32_t cvs[8][MAX_CVS][8];
    uint32_t ncv[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int j = 0; j < 8; j++) {
      if (!live[j] || mlen[j] <= CHUNK_LEN) continue;
      const uint8_t* msg = buf.data() + (size_t)j * (MSG_CAP + 1);
      const uint64_t n_full = mlen[j] / CHUNK_LEN;
      for (uint64_t c = 0; c < n_full; c++)
        ds[nd++] = {msg + c * CHUNK_LEN, c, (uint8_t)j, (uint8_t)c};
      ncv[j] = (uint32_t)(n_full + (mlen[j] % CHUNK_LEN ? 1 : 0));
    }
    int k = 0;
    for (; k + 8 <= nd; k += 8) {
      const uint8_t* p[8];
      uint64_t ctr[8];
      uint32_t out_cvs[8][8];
      for (int j = 0; j < 8; j++) {
        p[j] = ds[k + j].p;
        ctr[j] = ds[k + j].ctr;
      }
      wide::hash8_leaf_cvs_gather(p, ctr, out_cvs);
      for (int j = 0; j < 8; j++)
        std::memcpy(cvs[ds[k + j].lane][ds[k + j].ci], out_cvs[j], 32);
    }
    for (; k < nd; k++)
      leaf_chunk_cv(ds[k].p, CHUNK_LEN, ds[k].ctr,
                    cvs[ds[k].lane][ds[k].ci]);

    for (int j = 0; j < 8; j++) {
      if (!live[j]) continue;
      const int64_t i = small[(size_t)(g * 8 + j)];
      const uint8_t* msg = buf.data() + (size_t)j * (MSG_CAP + 1);
      if (mlen[j] <= CHUNK_LEN) {
        single_chunk_root(msg, (size_t)mlen[j], digests + i * 32);
      } else {
        const uint64_t n_full = mlen[j] / CHUNK_LEN;
        const uint64_t tail = mlen[j] % CHUNK_LEN;
        if (tail)
          leaf_chunk_cv(msg + n_full * CHUNK_LEN, (size_t)tail, n_full,
                        cvs[j][n_full]);
        merge_cvs_root(cvs[j], ncv[j], digests + i * 32);
      }
      status[i] = OK;
    }
  });
}
#endif  // __AVX2__

extern "C" {

// One-shot BLAKE3 of a buffer (32-byte digest).
void sd_blake3(const uint8_t* data, uint64_t len, uint8_t* out32) {
  Blake3 h;
  h.update(data, len);
  h.finalize(out32);
}

// Batched BLAKE3 over rows of a dense array. Row i hashes
// [optional 8-byte LE prefix_sizes[i]] ‖ payloads[i*stride .. +lens[i]].
// Groups of 8 equal-length rows go through the lockstep SIMD tree.
void sd_blake3_many(int64_t n, const uint8_t* payloads, int64_t stride,
                    const int32_t* lens, const uint64_t* prefix_sizes,
                    uint8_t* out, int n_threads) {
  // Grouping by 8 would starve workers when there are fewer groups than
  // cores — on multicore hosts small batches stay item-parallel.
  int hw = (int)std::thread::hardware_concurrency();
  if (hw <= 0) hw = 4;
  const int eff_threads = n_threads > 0 ? n_threads : hw;
  const int64_t n_groups =
      n >= (int64_t)eff_threads * 8 ? (n + 7) / 8 : n;
  const bool grouped = n_groups != n;
  parallel_for(n_groups, n_threads, [&](int64_t g) {
    if (!grouped) {
      const int64_t i = g;
      Blake3 h;
      if (prefix_sizes) {
        uint8_t pre[8];
        le64(prefix_sizes[i], pre);
        h.update(pre, 8);
      }
      h.update(payloads + i * stride, (size_t)lens[i]);
      h.finalize(out + i * 32);
      return;
    }
    const int64_t lo = g * 8;
    const int64_t hi = std::min<int64_t>(lo + 8, n);
#if defined(__AVX2__)
    if (hi - lo == 8) {
      bool uniform = true;
      for (int64_t i = lo + 1; i < hi; i++)
        if (lens[i] != lens[lo]) uniform = false;
      if (uniform) {
        const uint8_t* rows[8];
        for (int j = 0; j < 8; j++) rows[j] = payloads + (lo + j) * stride;
        wide::blake3_x8(rows,
                        (uint64_t)lens[lo] + (prefix_sizes ? 8 : 0),
                        prefix_sizes ? prefix_sizes + lo : nullptr,
                        out + lo * 32, 32);
        return;
      }
    }
#endif
    for (int64_t i = lo; i < hi; i++) {
      Blake3 h;
      if (prefix_sizes) {
        uint8_t pre[8];
        le64(prefix_sizes[i], pre);
        h.update(pre, 8);
      }
      h.update(payloads + i * stride, (size_t)lens[i]);
      h.finalize(out + i * 32);
    }
  });
}

// Stage a batch of large files: sampled 57,344-byte rows.
void sd_stage_large(int64_t n, const char** paths, const uint64_t* sizes,
                    uint8_t* out, int32_t* status, int n_threads) {
  parallel_for(n, n_threads, [&](int64_t i) {
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      status[i] = ERR_OPEN;
      return;
    }
    status[i] = read_sampled(fd, sizes[i], out + i * LARGE_PAYLOAD);
    close(fd);
  });
}

// Stage a batch of small files: whole-file rows of up to `cap` bytes.
void sd_stage_small(int64_t n, const char** paths, uint64_t cap, uint8_t* out,
                    int32_t* out_lens, int32_t* status, int n_threads) {
  parallel_for(n, n_threads, [&](int64_t i) {
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      status[i] = ERR_OPEN;
      return;
    }
    status[i] = read_small(fd, cap, out + i * (cap + 1), &out_lens[i]);
    close(fd);
  });
}

// Batched packed staging for the device CAS pipeline: one call stages a
// whole batch straight into the kernel's message rows — caller-owned,
// page-aligned pooled pages laid out [n, stride] (stride = the chunk
// grid for payload_cap, i.e. ceil((8 + payload_cap) / 1024) * 1024, and
// stride >= 8 + min(payload_cap, SMALL_WHOLE_CAP) + 1 when the batch
// carries small-class rows, for the grew-detection byte). Row i becomes
// le64(declared size) ‖ payload ‖ zeros with msg_lens[i] = 8 + payload
// bytes — exactly build_cas_messages' layout, with no intermediate
// Python bytes objects and no per-file memcpy on the host plane. Large
// rows (> MINIMUM_FILE_SIZE) take the 57,344-byte sampled payload via a
// shared mmap; small rows land whole via preadv. Per-row status lets
// the ctypes seam degrade file-by-file instead of failing the batch;
// any non-OK row is zeroed back to its 8-byte prefix so a reused pooled
// page can never leak a previous batch's bytes into a digest (the
// kernel consumes full 16-word blocks — residue would silently change
// it).
void sd_stage_batch(int64_t n, const char** paths, const uint64_t* sizes,
                    uint8_t* out, int64_t stride, uint64_t payload_cap,
                    int32_t* msg_lens, int32_t* status, int n_threads) {
  parallel_for(n, n_threads, [&](int64_t i) {
    uint8_t* row = out + i * stride;
    const uint64_t declared = sizes[i];
    le64(declared, row);
    uint64_t payload = 0;
    int32_t st;
    if (declared == 0) {
      st = ERR_EMPTY;  // no CAS ID for empty files (mod.rs:86)
    } else {
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) {
        st = ERR_OPEN;
      } else {
        if (declared > MINIMUM_FILE_SIZE && payload_cap >= LARGE_PAYLOAD) {
          st = read_sampled_mmap(fd, declared, row + 8);
          if (st == OK) payload = LARGE_PAYLOAD;
        } else {
          const uint64_t cap =
              payload_cap < SMALL_WHOLE_CAP ? payload_cap : SMALL_WHOLE_CAP;
          int32_t got = 0;
          st = read_small_v(fd, cap, row + 8, &got);
          if (st == OK) payload = (uint64_t)got;
        }
        close(fd);
      }
    }
    uint64_t keep = 8 + payload;
    if (st != OK) keep = 8;  // error/empty rows: prefix only, rest zeroed
    std::memset(row + keep, 0, (size_t)((uint64_t)stride - keep));
    msg_lens[i] = (int32_t)keep;
    status[i] = st;
  });
}

// Fused CPU CAS path: stage + hash in one pass, one thread-hop per file.
// digests[i] is the 32-byte blake3(size_le ‖ sampled-or-whole payload);
// the caller truncates to 16 hex chars (cas.rs:61).
// Large files all share the 57,344-byte sampled payload shape, so they
// are staged and hashed in lockstep groups of 8 (wide::blake3_x8).
void sd_cas_digests(int64_t n, const char** paths, const uint64_t* sizes,
                    uint8_t* digests, int32_t* status, int n_threads) {
  // Lanes fully handled by a batched path below; distinct byte writes
  // from the group workers are race-free, and the scalar sweep at the
  // end picks up whatever stayed 0 (group remainders, grown files).
  std::vector<uint8_t> done((size_t)n, 0);
#if defined(__AVX2__)
  std::vector<int64_t> large;
  large.reserve((size_t)n);
  for (int64_t i = 0; i < n; i++)
    if (sizes[i] > MINIMUM_FILE_SIZE) large.push_back(i);
  const int64_t n_lgroups = (int64_t)large.size() / 8;
  parallel_for(n_lgroups, n_threads, [&](int64_t g) {
    thread_local std::vector<uint8_t> buf;  // reused across groups
    if (buf.size() < (size_t)8 * LARGE_PAYLOAD)
      buf.resize((size_t)8 * LARGE_PAYLOAD);
    const uint8_t* rows[8];
    uint64_t prefixes[8];
    bool all_ok = true;
    for (int j = 0; j < 8; j++) {
      const int64_t i = large[(size_t)(g * 8 + j)];
      done[(size_t)i] = 1;
      uint8_t* row = buf.data() + (size_t)j * LARGE_PAYLOAD;
      rows[j] = row;
      prefixes[j] = sizes[i];
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) {
        status[i] = ERR_OPEN;
        all_ok = false;
        continue;
      }
      status[i] = read_sampled(fd, sizes[i], row);
      close(fd);
      if (status[i] != OK) all_ok = false;
    }
    if (all_ok) {
      uint8_t dg[8 * 32];
      wide::blake3_x8(rows, 8 + LARGE_PAYLOAD, prefixes, dg, 32);
      for (int j = 0; j < 8; j++)
        std::memcpy(digests + large[(size_t)(g * 8 + j)] * 32, dg + j * 32,
                    32);
    } else {
      for (int j = 0; j < 8; j++) {
        const int64_t i = large[(size_t)(g * 8 + j)];
        if (status[i] != OK) continue;
        Blake3 h;
        uint8_t pre[8];
        le64(sizes[i], pre);
        h.update(pre, 8);
        h.update(rows[j], LARGE_PAYLOAD);
        h.finalize(digests + i * 32);
      }
    }
  });

  // Small files (whole-file messages, cas.rs:27) batched 8 per group
  // with their full 1024-byte chunks pooled across the group.
  std::vector<int64_t> small;
  small.reserve((size_t)n);
  for (int64_t i = 0; i < n; i++)
    if (sizes[i] != 0 && sizes[i] <= MINIMUM_FILE_SIZE) small.push_back(i);
  hash_small_whole_groups(small, paths, sizes, digests, status, done,
                          n_threads);
#endif
  parallel_for(n, n_threads, [&](int64_t i) {
    if (done[(size_t)i]) return;
    if (sizes[i] == 0) {
      status[i] = ERR_EMPTY;
      return;
    }
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      status[i] = ERR_OPEN;
      return;
    }
    Blake3 h;
    uint8_t pre[8];
    le64(sizes[i], pre);
    h.update(pre, 8);
    if (sizes[i] > MINIMUM_FILE_SIZE) {
      uint8_t row[LARGE_PAYLOAD];
      int32_t s = read_sampled(fd, sizes[i], row);
      if (s != OK) {
        status[i] = s;
        close(fd);
        return;
      }
      h.update(row, LARGE_PAYLOAD);
    } else {
      // Whole file regardless of declared size (fs::read in cas.rs:27).
      uint8_t buf[1 << 16];
      uint64_t off = 0;
      for (;;) {
        ssize_t r = pread(fd, buf, sizeof(buf), (off_t)off);
        if (r < 0) {
          status[i] = ERR_IO;
          close(fd);
          return;
        }
        if (r == 0) break;
        h.update(buf, (size_t)r);
        off += (uint64_t)r;
      }
    }
    h.finalize(digests + i * 32);
    status[i] = OK;
    close(fd);
  });
}

// Full-file checksums, 1 MiB streaming blocks (validation/hash.rs:10-24).
// `sizes_hint` (nullable) routes files to the batched small path without
// any stat — callers like the validator already hold sizes from the DB.
// The hint only PARTITIONS: a hinted-small file that is actually larger
// than the cap is detected at read time and re-streamed, so a stale hint
// costs one wasted read, never a wrong digest.
void sd_checksum_files(int64_t n, const char** paths,
                       const uint64_t* sizes_hint, uint8_t* digests,
                       int32_t* status, int n_threads) {
  (void)sizes_hint;  // partition hint is AVX2-path-only
  std::vector<uint8_t> done((size_t)n, 0);
#if defined(__AVX2__)
  // Small regular files go through the cross-file chunk-pooled groups
  // (no size prefix — validation/hash.rs hashes content only); files a
  // stat can't see or that grow past the cap stream below as before.
  // Without a hint, stat in parallel — a serial pre-pass over 1M paths
  // would gate the whole call on one thread's syscall loop.
  std::vector<uint64_t> stat_sizes;
  if (!sizes_hint) {
    stat_sizes.assign((size_t)n, UINT64_MAX);  // sentinel: stream it
    parallel_for(n, n_threads, [&](int64_t i) {
      struct stat st;
      if (stat(paths[i], &st) == 0 && S_ISREG(st.st_mode))
        stat_sizes[(size_t)i] = (uint64_t)st.st_size;
    });
  }
  const uint64_t* part = sizes_hint ? sizes_hint : stat_sizes.data();
  std::vector<int64_t> small;
  small.reserve((size_t)n);
  for (int64_t i = 0; i < n; i++)
    if (part[i] <= SMALL_WHOLE_CAP) small.push_back(i);
  hash_small_whole_groups(small, paths, nullptr, digests, status, done,
                          n_threads);
#endif
  parallel_for(n, n_threads, [&](int64_t i) {
    if (done[(size_t)i]) return;
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      status[i] = ERR_OPEN;
      return;
    }
    std::vector<uint8_t> buf(CHECKSUM_BLOCK);
    Blake3 h;
    uint64_t off = 0;
    for (;;) {
      ssize_t r = pread(fd, buf.data(), buf.size(), (off_t)off);
      if (r < 0) {
        status[i] = ERR_IO;
        close(fd);
        return;
      }
      if (r == 0) break;
      h.update(buf.data(), (size_t)r);
      off += (uint64_t)r;
    }
    h.finalize(digests + i * 32);
    status[i] = OK;
    close(fd);
  });
}

// ---------------------------------------------------------------------------
// Batched op-log encoding (the sync plane's msgpack hot path).
//
// sd_encode_ops emits one shared_op_blob `data` payload for a whole
// bulk-writer chunk: a msgpack array of per-op
//   [timestamp(uint), record_id(bin 18), kind(str), payload(bin)]
// entries, where payload is the canonical op_payload dict packing for
// the field-is-None shapes (create / multi-field update) — BYTE-
// IDENTICAL to the Python fragment encoder in
// spacedrive_tpu/sync/opblob.py, which is both the fallback and the
// parity oracle (tests/test_sync_blob.py). The minimal-width msgpack
// emitters below must match msgpack-python's packb output exactly.
// ---------------------------------------------------------------------------

static inline uint8_t* mp_be(uint8_t* p, uint64_t v, int nbytes) {
  for (int i = nbytes - 1; i >= 0; i--) *p++ = (uint8_t)(v >> (8 * i));
  return p;
}

static inline uint8_t* mp_uint(uint8_t* p, uint64_t v) {
  if (v < 0x80) { *p++ = (uint8_t)v; return p; }
  if (v < 0x100) { *p++ = 0xcc; return mp_be(p, v, 1); }
  if (v < 0x10000) { *p++ = 0xcd; return mp_be(p, v, 2); }
  if (v <= 0xFFFFFFFFull) { *p++ = 0xce; return mp_be(p, v, 4); }
  *p++ = 0xcf;
  return mp_be(p, v, 8);
}

static inline uint8_t* mp_bin_hdr(uint8_t* p, uint64_t len) {
  if (len < 0x100) { *p++ = 0xc4; return mp_be(p, len, 1); }
  if (len < 0x10000) { *p++ = 0xc5; return mp_be(p, len, 2); }
  *p++ = 0xc6;
  return mp_be(p, len, 4);
}

static inline uint8_t* mp_str(uint8_t* p, const char* s, size_t len) {
  if (len < 32) {
    *p++ = (uint8_t)(0xa0 | len);
  } else if (len < 0x100) {
    *p++ = 0xd9;
    *p++ = (uint8_t)len;
  } else {
    *p++ = 0xda;
    p = mp_be(p, len, 2);
  }
  std::memcpy(p, s, len);
  return p + len;
}

// Mirrors of sync/opblob.py's BULK_* fragments (op_payload key order).
static const uint8_t OP_HDR5[23] = {
    0x85, 0xa5, 'f', 'i', 'e', 'l', 'd', 0xc0, 0xa5, 'v', 'a', 'l',
    'u', 'e', 0xc0, 0xa6, 'd', 'e', 'l', 'e', 't', 'e', 0xc2};
static const uint8_t OP_HDR6[23] = {
    0x86, 0xa5, 'f', 'i', 'e', 'l', 'd', 0xc0, 0xa5, 'v', 'a', 'l',
    'u', 'e', 0xc0, 0xa6, 'd', 'e', 'l', 'e', 't', 'e', 0xc2};
static const uint8_t OP_OPID[8] = {0xa5, 'o', 'p', '_', 'i', 'd',
                                   0xc4, 0x10};
static const uint8_t OP_VALUES[7] = {0xa6, 'v', 'a', 'l', 'u', 'e', 's'};
static const uint8_t OP_UPDATE_T[8] = {0xa6, 'u', 'p', 'd', 'a', 't',
                                       'e', 0xc3};

// Encode n ops of one uniform kind into a blob. record_ids/op_ids are
// dense n×16 arrays (pub ids — the only shape bulk writers emit);
// values_buf holds the pre-packed msgpack of each op's values dict,
// sliced by values_offsets[n+1]. Returns bytes written, or -1 when
// out_cap is too small (callers over-allocate; -1 is a logic error).
int64_t sd_encode_ops(int64_t n, const uint64_t* timestamps,
                      const uint8_t* record_ids, const char* kind,
                      const uint8_t* op_ids, const uint8_t* values_buf,
                      const int64_t* values_offsets, uint8_t* out,
                      int64_t out_cap) {
  const size_t klen = std::strlen(kind);
  const bool update = klen >= 2 && kind[0] == 'u' && kind[1] == ':';
  uint8_t* p = out;
  const uint8_t* end = out + out_cap;
  if (out_cap < 8) return -1;
  if (n < 16) {
    *p++ = (uint8_t)(0x90 | n);
  } else if (n < 65536) {
    *p++ = 0xdc;
    p = mp_be(p, (uint64_t)n, 2);
  } else {
    *p++ = 0xdd;
    p = mp_be(p, (uint64_t)n, 4);
  }
  for (int64_t i = 0; i < n; i++) {
    const int64_t vlen = values_offsets[i + 1] - values_offsets[i];
    const uint64_t plen =
        sizeof(OP_HDR5) + sizeof(OP_OPID) + 16 + sizeof(OP_VALUES) +
        (uint64_t)vlen + (update ? sizeof(OP_UPDATE_T) : 0);
    // worst-case framing: 1 (fixarray) + 9 (uint64) + 20 (rid bin) +
    // 3+klen (str) + 5 (payload bin hdr) + plen
    if (p + 38 + klen + plen > end) return -1;
    *p++ = 0x94;  // [ts, rid, kind, payload]
    p = mp_uint(p, timestamps[i]);
    *p++ = 0xc4;  // bin8(18): msgpack-packed 16-byte pub id
    *p++ = 18;
    *p++ = 0xc4;
    *p++ = 0x10;
    std::memcpy(p, record_ids + i * 16, 16);
    p += 16;
    p = mp_str(p, kind, klen);
    p = mp_bin_hdr(p, plen);
    std::memcpy(p, update ? OP_HDR6 : OP_HDR5, sizeof(OP_HDR5));
    p += sizeof(OP_HDR5);
    std::memcpy(p, OP_OPID, sizeof(OP_OPID));
    p += sizeof(OP_OPID);
    std::memcpy(p, op_ids + i * 16, 16);
    p += 16;
    std::memcpy(p, OP_VALUES, sizeof(OP_VALUES));
    p += sizeof(OP_VALUES);
    std::memcpy(p, values_buf + values_offsets[i], (size_t)vlen);
    p += vlen;
    if (update) {
      std::memcpy(p, OP_UPDATE_T, sizeof(OP_UPDATE_T));
      p += sizeof(OP_UPDATE_T);
    }
  }
  return p - out;
}

// ---------------------------------------------------------------------------
// Batched op-log decoding (the clone fast path's msgpack hot path).
//
// sd_decode_ops is the inverse of sd_encode_ops, but GENERAL: it parses
// any blob the Python reference encoder (opblob.encode_entries) can
// emit, not just the uniform bulk shapes. Instead of materializing
// per-op Python objects it fills dense offset/length arrays pointing
// INTO the caller's blob buffer — the ctypes wrapper slices lazily and
// the batched fresh-peer apply consumes record ids / payloads / values
// as zero-copy views. For payloads matching the uniform bulk shapes
// (OP_HDR5/6 fragments) it additionally locates the op_id and the
// packed `values` map so the apply path never decodes the payload's
// outer dict at all. Byte-parity with the pure-Python decoder
// (opblob.decode_entries_py) is asserted in tests/test_sync_blob.py.
// ---------------------------------------------------------------------------

namespace {

// Cursor over the blob; every reader checks bounds and fails closed.
struct MpCur {
  const uint8_t* p;
  const uint8_t* end;
  bool ok;
  uint8_t peek() const { return *p; }
  bool need(size_t n) {
    if ((size_t)(end - p) < n) ok = false;
    return ok;
  }
  uint64_t be(int n) {  // big-endian uint of n bytes, advances
    uint64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | *p++;
    return v;
  }
};

// msgpack uint (the only timestamp shape the encoders emit).
static bool mp_read_uint(MpCur& c, uint64_t* out) {
  if (!c.need(1)) return false;
  uint8_t t = *c.p++;
  if (t < 0x80) { *out = t; return true; }
  int n = 0;
  switch (t) {
    case 0xcc: n = 1; break;
    case 0xcd: n = 2; break;
    case 0xce: n = 4; break;
    case 0xcf: n = 8; break;
    default: return false;
  }
  if (!c.need(n)) return false;
  *out = c.be(n);
  return true;
}

// msgpack bin: content offset/length (bin8/16/32).
static bool mp_read_bin(MpCur& c, const uint8_t* base, int64_t* off,
                        int64_t* len) {
  if (!c.need(1)) return false;
  uint8_t t = *c.p++;
  int n = 0;
  switch (t) {
    case 0xc4: n = 1; break;
    case 0xc5: n = 2; break;
    case 0xc6: n = 4; break;
    default: return false;
  }
  if (!c.need(n)) return false;
  uint64_t l = c.be(n);
  if (!c.need(l)) return false;
  *off = c.p - base;
  *len = (int64_t)l;
  c.p += l;
  return true;
}

// msgpack str: content offset/length (fixstr/str8/str16).
static bool mp_read_str(MpCur& c, const uint8_t* base, int64_t* off,
                        int32_t* len) {
  if (!c.need(1)) return false;
  uint8_t t = *c.p++;
  uint64_t l;
  if ((t & 0xe0) == 0xa0) {
    l = t & 0x1f;
  } else if (t == 0xd9) {
    if (!c.need(1)) return false;
    l = c.be(1);
  } else if (t == 0xda) {
    if (!c.need(2)) return false;
    l = c.be(2);
  } else {
    return false;
  }
  if (!c.need(l)) return false;
  *off = c.p - base;
  *len = (int32_t)l;
  c.p += l;
  return true;
}

}  // namespace

// Decode a shared_op_blob page of up to max_n entries. Per entry i the
// arrays receive: ts[i]; rid/kind/payload content offset+length into
// `data`; and — when the payload matches a uniform bulk shape —
// opid_off[i] (16-byte op id), values_off/len[i] (the packed values
// map) and flags[i] (bit0 = uniform, bit1 = update), else flags[i]=0
// and opid_off[i]=-1. Returns the entry count, or a negative Status
// (ERR_IO) on malformed input — the wrapper falls back to the Python
// decoder rather than trusting a partial parse.
int64_t sd_decode_ops(const uint8_t* data, int64_t len, int64_t max_n,
                      uint64_t* ts, int64_t* rid_off, int32_t* rid_len,
                      int64_t* kind_off, int32_t* kind_len,
                      int64_t* payload_off, int64_t* payload_len,
                      int64_t* opid_off, int64_t* values_off,
                      int64_t* values_len, uint8_t* flags) {
  MpCur c{data, data + len, true};
  if (!c.need(1)) return ERR_IO;
  uint8_t t = *c.p++;
  uint64_t n;
  if ((t & 0xf0) == 0x90) {
    n = t & 0x0f;
  } else if (t == 0xdc) {
    if (!c.need(2)) return ERR_IO;
    n = c.be(2);
  } else if (t == 0xdd) {
    if (!c.need(4)) return ERR_IO;
    n = c.be(4);
  } else {
    return ERR_IO;
  }
  if ((int64_t)n > max_n) return ERR_IO;
  for (uint64_t i = 0; i < n; i++) {
    if (!c.need(1) || *c.p++ != 0x94) return ERR_IO;  // [ts,rid,kind,pl]
    if (!mp_read_uint(c, &ts[i])) return ERR_IO;
    int64_t rl = 0;
    if (!mp_read_bin(c, data, &rid_off[i], &rl)) return ERR_IO;
    if (rl > INT32_MAX) return ERR_IO;
    rid_len[i] = (int32_t)rl;
    if (!mp_read_str(c, data, &kind_off[i], &kind_len[i])) return ERR_IO;
    if (!mp_read_bin(c, data, &payload_off[i], &payload_len[i]))
      return ERR_IO;
    // Uniform-shape probe: HDR5/6 ‖ OPID ‖ 16 ‖ VALUES ‖ values
    // [‖ UPDATE_T]. Anything else is still a valid entry — the apply
    // path just takes its per-op fallback for it.
    flags[i] = 0;
    opid_off[i] = -1;
    values_off[i] = -1;
    values_len[i] = 0;
    const uint8_t* pl = data + payload_off[i];
    const int64_t pn = payload_len[i];
    const int64_t fixed = (int64_t)(sizeof(OP_HDR5) + sizeof(OP_OPID) +
                                    16 + sizeof(OP_VALUES));
    if (pn < fixed + 1) continue;
    bool update;
    if (std::memcmp(pl, OP_HDR5, sizeof(OP_HDR5)) == 0) {
      update = false;
    } else if (std::memcmp(pl, OP_HDR6, sizeof(OP_HDR6)) == 0) {
      update = true;
    } else {
      continue;
    }
    const uint8_t* q = pl + sizeof(OP_HDR5);
    if (std::memcmp(q, OP_OPID, sizeof(OP_OPID)) != 0) continue;
    q += sizeof(OP_OPID);
    const int64_t oid = payload_off[i] + (q - pl);
    q += 16;
    if (std::memcmp(q, OP_VALUES, sizeof(OP_VALUES)) != 0) continue;
    q += sizeof(OP_VALUES);
    int64_t vlen = pn - (q - pl);
    if (update) {
      vlen -= (int64_t)sizeof(OP_UPDATE_T);
      if (vlen < 1 || std::memcmp(pl + pn - sizeof(OP_UPDATE_T),
                                  OP_UPDATE_T, sizeof(OP_UPDATE_T)) != 0)
        continue;
    }
    if (vlen < 1) continue;
    opid_off[i] = oid;
    values_off[i] = payload_off[i] + (q - pl);
    values_len[i] = vlen;
    flags[i] = update ? 3 : 1;
  }
  if (c.p != c.end) return ERR_IO;  // trailing garbage
  return (int64_t)n;
}

// Secure erase: `passes` overwrites with a keystream then zeros, fsync'd
// (the role of sd-crypto's fs/erase.rs behind the file_eraser job).
int32_t sd_secure_erase(const char* path, int passes) {
  int fd = open(path, O_WRONLY);
  if (fd < 0) return ERR_OPEN;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return ERR_IO;
  }
  uint64_t size = (uint64_t)st.st_size;
  std::vector<uint8_t> block(1 << 16);
  uint64_t x = 0x9E3779B97F4A7C15ull ^ size;
  for (int p = 0; p < passes + 1; p++) {
    bool zeros = (p == passes);  // final pass is zeros
    uint64_t off = 0;
    while (off < size) {
      size_t len = (size_t)std::min<uint64_t>(block.size(), size - off);
      if (zeros) {
        std::memset(block.data(), 0, len);
      } else {
        for (size_t i = 0; i + 8 <= block.size(); i += 8) {
          // xorshift64* keystream — overwrite data, not cryptography.
          x ^= x >> 12;
          x ^= x << 25;
          x ^= x >> 27;
          uint64_t v = x * 0x2545F4914F6CDD1Dull;
          std::memcpy(block.data() + i, &v, 8);
        }
      }
      ssize_t w = pwrite(fd, block.data(), len, (off_t)off);
      if (w != (ssize_t)len) {
        close(fd);
        return ERR_IO;
      }
      off += (uint64_t)w;
    }
    fsync(fd);
  }
  close(fd);
  return OK;
}

}  // extern "C"

#if defined(SDIO_STAGE_SELFTEST)
// `make stage` self-test: stage a synthetic mixed batch through
// sd_stage_batch and verify layout, statuses and byte content against
// the spec, with no Python in the loop. Exercises: large sampled row
// (header/sample/footer offsets), small whole row, empty row, missing
// path, short large file, and tail zeroing over a dirtied buffer.
#include <cstdio>
#include <cstdlib>
#include <string>

namespace {

bool write_pattern(const std::string& p, uint64_t n) {
  FILE* f = fopen(p.c_str(), "wb");
  if (!f) return false;
  for (uint64_t i = 0; i < n; i++) {
    uint8_t b = (uint8_t)((i * 131) ^ (i >> 8));
    if (fwrite(&b, 1, 1, f) != 1) {
      fclose(f);
      return false;
    }
  }
  fclose(f);
  return true;
}

uint8_t pat(uint64_t i) { return (uint8_t)((i * 131) ^ (i >> 8)); }

int fail(const char* what) {
  fprintf(stderr, "sd_stage_batch self-test FAILED: %s\n", what);
  return 1;
}

}  // namespace

int main() {
  char tmpl[] = "/tmp/sdio-stage-XXXXXX";
  if (!mkdtemp(tmpl)) return fail("mkdtemp");
  const std::string dir = tmpl;
  const uint64_t large_n = MINIMUM_FILE_SIZE + 50000;  // 152,400 B
  const std::string large_p = dir + "/large.bin";
  const std::string small_p = dir + "/small.bin";
  const std::string empty_p = dir + "/empty.bin";
  const std::string short_p = dir + "/short.bin";
  if (!write_pattern(large_p, large_n)) return fail("write large");
  if (!write_pattern(small_p, 5000)) return fail("write small");
  if (!write_pattern(empty_p, 0)) return fail("write empty");
  if (!write_pattern(short_p, 4096)) return fail("write short large");

  constexpr int64_t N = 5;
  const std::string missing = dir + "/missing.bin";
  const char* paths[N] = {large_p.c_str(), small_p.c_str(), empty_p.c_str(),
                          missing.c_str(), short_p.c_str()};
  const uint64_t sizes[N] = {large_n, 5000, 0, 5000, large_n};
  // Mixed batch → the small grid: ceil((8 + 102400) / 1024) = 101.
  const int64_t stride = 101 * 1024;
  std::vector<uint8_t> buf((size_t)(N * stride), 0xAB);  // dirty pool page
  int32_t lens[N], status[N];
  sd_stage_batch(N, paths, sizes, buf.data(), stride, SMALL_WHOLE_CAP, lens,
                 status, 0);

  if (status[0] != OK || lens[0] != (int32_t)(8 + LARGE_PAYLOAD))
    return fail("large row status/len");
  if (status[1] != OK || lens[1] != 8 + 5000) return fail("small row");
  if (status[2] != ERR_EMPTY || lens[2] != 8) return fail("empty row");
  if (status[3] != ERR_OPEN) return fail("missing row");
  if (status[4] != ERR_SHORT_READ) return fail("short-read row");

  const uint8_t* r0 = buf.data();
  uint64_t pre = 0;
  std::memcpy(&pre, r0, 8);
  if (pre != large_n) return fail("large prefix");
  // Header bytes, then the first sample (offset HEADER + 0*jump — the
  // contiguous continuation), then the footer relative to real EOF.
  for (uint64_t i = 0; i < HEADER_OR_FOOTER_SIZE; i++)
    if (r0[8 + i] != pat(i)) return fail("large header bytes");
  for (uint64_t i = 0; i < SAMPLE_SIZE; i++)
    if (r0[8 + HEADER_OR_FOOTER_SIZE + i] != pat(HEADER_OR_FOOTER_SIZE + i))
      return fail("large sample bytes");
  const uint64_t foot0 = large_n - HEADER_OR_FOOTER_SIZE;
  const uint64_t foot_row = 8 + HEADER_OR_FOOTER_SIZE +
                            SAMPLE_COUNT * SAMPLE_SIZE;
  for (uint64_t i = 0; i < HEADER_OR_FOOTER_SIZE; i++)
    if (r0[foot_row + i] != pat(foot0 + i)) return fail("large footer bytes");
  for (int64_t i = lens[0]; i < stride; i++)
    if (r0[i] != 0) return fail("large tail not zeroed");

  const uint8_t* r1 = buf.data() + stride;
  for (uint64_t i = 0; i < 5000; i++)
    if (r1[8 + i] != pat(i)) return fail("small bytes");
  for (int64_t i = lens[1]; i < stride; i++)
    if (r1[i] != 0) return fail("small tail not zeroed");
  // Error/empty rows must be scrubbed back to their prefix: a reused
  // pooled page must never leak prior bytes through a failed row.
  for (int64_t r = 2; r < N; r++)
    for (int64_t i = 8; i < stride; i++)
      if (buf[(size_t)(r * stride + i)] != 0) return fail("error row residue");

  printf("sd_stage_batch self-test: OK\n");
  return 0;
}
#endif  // SDIO_STAGE_SELFTEST
