"""Round benchmark: batched CAS-ID generation throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The workload is the FileIdentifierJob hot kernel (SURVEY.md §3.3): for a
batch of large files, hash the 8-byte size prefix + 57,344 sampled bytes
with BLAKE3 and truncate to 16 hex chars
(/root/reference/core/src/object/cas.rs:23-62 semantics).

`vs_baseline` is the speedup over THIS REPO'S NATIVE C++ AVX2 PLANE
(native/sdio.cpp `sd_blake3_many`, 8-way message-parallel AVX2 lanes) on
the bench host's CPU — the honest stand-in for the reference's CPU path
(the SIMD `blake3` crate behind cas.rs; the reference publishes no
numbers, BASELINE.md). Round 1 compared against the repo's numpy
fallback, which inflated the ratio ~8×; this baseline is the fastest
CPU implementation in the repo.

Timing methodology: the device number chains ITERS kernel executions
inside one jitted scan with a loop-carried dependency, timed with a
single D2H sync — per-call wall timing through the axon tunnel measures
RPC latency, not the kernel (tools/perf_probe.py documents this). The
kernel number excludes H2D; `h2d_gbps` and `e2e_overlapped_files_per_sec`
(steady-state depth-N pipeline = max(transfer, compute) per device
stream) are reported alongside so the end-to-end story is explicit.
"""

from __future__ import annotations

import json
import time

import numpy as np

# Batch size amortizes the chip's per-dispatch overhead (measured
# ~7-10 ms under load on the shared bench chip): 2048 → ~0.3-0.5M
# files/s, 16384 → ~1.1M files/s with the same kernel. 16 K files is
# also the identifier's device step size (ops/staging.AUTO_DEVICE_BATCH).
# ITERS amortizes the ~74 ms fixed RPC+sync cost of ONE timed call
# through the tunnel (tools/kernel_ceiling.py sweep: per-iteration time
# is t_fixed/ITERS + 6.6 ms marginal at B=16K, i.e. the kernel's
# sustained rate is ~2.5M files/s; ITERS=60 reports within ~12% of it,
# while keeping each timed program well under the tunnel worker's
# multi-second crash threshold).
B = 16384
ITERS = 60
MSG_BYTES = 57352  # 8-byte size prefix + 57,344 sampled bytes


def main() -> None:
    from spacedrive_tpu.ops import blake3_jax as bj

    rng = np.random.default_rng(0)
    payloads = rng.integers(0, 256, size=(B, 57344), dtype=np.uint8)
    sizes = rng.integers(200_000, 50_000_000, size=B).astype(np.uint64)
    words, lengths = bj.build_cas_messages(payloads, sizes)

    # Device path: pallas kernel on TPU (blake3_words dispatches), timed
    # as ITERS chained executions inside one jit (see module docstring).
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    @jax.jit
    def looped(w, l):
        def body(acc, _):
            out = bj._blake3_impl_best(w, l | (acc[0, 0] & 1).astype(l.dtype))
            return out, None
        acc, _ = lax.scan(body, jnp.zeros((B, 8), jnp.uint32),
                          None, length=ITERS)
        return acc

    w = jax.device_put(words)
    l = jax.device_put(lengths)
    r = looped(w, l)
    np.asarray(r.ravel()[0])  # compile + warm (block_until_ready lies on axon)
    t_kernel = float("inf")
    for _ in range(3):  # best-of-3: the tunnel adds run-to-run spread
        t0 = time.perf_counter()
        r = looped(w, l)
        np.asarray(r.ravel()[0])
        t_kernel = min(t_kernel, (time.perf_counter() - t0) / ITERS)
    device_fps = B / t_kernel

    # Correctness spot check against the streaming oracle.
    out = bj.blake3_words(words, lengths)
    cas_ids = bj.digests_to_cas_ids(out)
    from spacedrive_tpu.ops.cas import cas_id_of_payload

    for i in (0, B // 2, B - 1):
        expect = cas_id_of_payload(int(sizes[i]), payloads[i].tobytes())
        assert cas_ids[i] == expect, (i, cas_ids[i], expect)

    # Honest CPU baseline: the repo's AVX2 C++ plane, same messages.
    from spacedrive_tpu import native

    if native.available():
        lens = np.full(B, payloads.shape[1], np.int32)
        native.blake3_many(payloads[:64], lens[:64], sizes[:64])  # warm
        cpu_fps = 0.0
        for _ in range(3):  # best-of-3, symmetric with the device side
            t0 = time.perf_counter()
            native.blake3_many(payloads, lens, sizes)
            cpu_fps = max(cpu_fps, B / (time.perf_counter() - t0))
        baseline_name = "native C++ AVX2 blake3_many (this repo, bench host CPU)"
    else:  # no native build: fall back to numpy (and say so)
        from spacedrive_tpu.ops import blake3_batch as bb

        t0 = time.perf_counter()
        bb.blake3_batch(np, words[:128], lengths[:128])
        cpu_fps = 128 / (time.perf_counter() - t0)
        baseline_name = "numpy batched blake3 (native plane unavailable)"

    # H2D link measurement (marker-synced full fetch; a sliced fetch
    # would compile a second program through the tunnel). A 117 MB
    # slice ×2 instead of the full 956 MB ×3 — on the tunnel's bad
    # days (0.02 GB/s) the full probe alone runs 4+ minutes and blows
    # the bench timeout; the per-byte rate is what matters.
    probe = np.ascontiguousarray(words[:2048])
    np.asarray(jax.device_put(np.zeros(16, np.uint8)))  # warm the path
    t0 = time.perf_counter()
    for _ in range(2):  # fixed sync cost alone (~74 ms RPC)
        np.asarray(jax.device_put(np.zeros(16, np.uint8)))
    t_sync = (time.perf_counter() - t0) / 2
    t0 = time.perf_counter()
    for _ in range(2):
        jax.device_put(probe)
        np.asarray(jax.device_put(np.zeros(16, np.uint8)))
    per_probe = (time.perf_counter() - t0) / 2
    # scale only the TRANSFER portion by the byte ratio — extrapolating
    # the fixed sync overhead would understate fast links ~35%
    t_h2d = (max(per_probe - t_sync, 1e-4)
             * (words.nbytes / probe.nbytes) + t_sync)

    # MEASURED depth-N pipeline (ops/overlap.py): concurrent C++ staging
    # of batches i+1..i+k overlaps H2D+kernel of batch i across the
    # device ring, digests retired with a one-batch lag.
    # Corpus is sparse files sized so the run is ~20-40 s
    # at the probed link speed (the sum of stage+transfer+kernel serial
    # would be strictly larger; the bound field is what a perfect
    # pipeline would sustain from the same run's component times).
    import shutil
    import tempfile

    from spacedrive_tpu.ops import overlap

    link_bps = words.nbytes / t_h2d
    # Thin-link days (the tunnel swings 1.5 MB/s – 1.2 GB/s): shrink
    # the per-batch payload so the pipeline + its two calibration
    # brackets stay inside the bench timeout. The steady-state shape
    # is unchanged — only fewer files per batch.
    pb = 2048 if link_bps >= 50e6 else 512
    per_batch_s = pb * MSG_BYTES / max(link_bps, 1e6)
    n_batches = int(max(3, min(12, 30.0 / max(per_batch_s, 0.25))))
    proot = tempfile.mkdtemp(prefix="sdtpu-overlap-")
    try:
        pipeline_batches = overlap.make_sparse_corpus(
            proot, pb * n_batches, 120_000, pb)
        _res, pstats = overlap.run_overlapped(pipeline_batches)
    finally:
        shutil.rmtree(proot, ignore_errors=True)
    e2e_fps = pstats.files_per_sec          # measured, not a formula
    breport = pstats.bound_report()         # same-run bound accounting

    # Static instruction mix per 64-byte compression (docs/architecture.md
    # round-4 accounting, cross-checked by tools/vpu_opclass_probe.py):
    # 7 rounds x 8 G x (6 add + 4 xor + 4 rot), rotate lowered on the
    # VPU as shift+shift+or = 3 ops plus the 8-xor output fold -> 1,240 ALU ops (+168 roll moves,
    # excluded here: data movement, not ALU issue). 57x16 block
    # compressions + 56 tree parents per large file.
    ops_per_file = (57 * 16 + 56) * 1240
    util = device_fps * ops_per_file / 5e12

    print(json.dumps({
        "metric": "cas_ids_per_sec_large_files",
        "value": round(device_fps, 1),
        "unit": "files/s",
        "vs_baseline": round(device_fps / cpu_fps, 2),
        "baseline": baseline_name,
        "baseline_files_per_sec": round(cpu_fps, 1),
        "bytes_per_sec": round(device_fps * MSG_BYTES, 0),
        "h2d_gbps": round(words.nbytes / t_h2d / 1e9, 2),
        "e2e_overlapped_files_per_sec": round(e2e_fps, 1),
        "e2e_overlapped_bound_files_per_sec":
            round(pstats.bound_files_per_sec, 1),
        # Same-run bound accounting (VERDICT r5 weak #1): calibration
        # now interleaves with the measurement (ops/overlap.py pauses
        # the pipeline mid-run), so measured-vs-bound compares within
        # one weather window; when measured still lands < 0.9× bound,
        # `reason` explains it from THIS run's calibration spread.
        "e2e_overlapped_bound_ratio": breport["ratio"],
        "e2e_overlap_calibrations": breport["calibrations"],
        "e2e_overlap_binding_spread": breport["binding_component_spread"],
        "e2e_overlapped_bound_reason": breport["reason"],
        # Depth-N pipeline shape of the measured run: how many batches
        # were in flight, across which device ring, and how much of the
        # staged footprint the donated kernel recycled.
        "pipeline_depth": pstats.depth,
        "pipeline_depth_high_water": pstats.depth_high_water,
        "pipeline_devices": pstats.n_devices,
        "pipeline_per_device_batches": pstats.per_device_batches,
        "pipeline_donated": pstats.donate,
        "pipeline_donated_reuse": pstats.donated_reuse,
        "pipeline_h2d_gbps_measured":
            round(pstats.h2d_bytes / pstats.h2d_s / 1e9, 3)
            if pstats.h2d_s else 0.0,
        "pipeline_stall_s": {
            "stage": round(pstats.stage_s, 3),
            "retire": round(pstats.retire_stall_s, 3),
            "calibration": round(pstats.calibration_s, 3),
        },
        "e2e_overlap_components_s": {
            "stage": round(pstats.t_stage_1, 3),
            "h2d": round(pstats.t_h2d_1, 3),
            "kernel_fetch": round(pstats.t_kernel_1, 3),
            "stage_post": round(pstats.t_stage_2, 3),
            "h2d_post": round(pstats.t_h2d_2, 3),
            "kernel_fetch_post": round(pstats.t_kernel_2, 3),
        },
        "vpu_utilization_est": round(util, 3),
        # Accounting version so cross-round utilization numbers compare
        # like-for-like: r4 changed ops/compression 840→1,240 (rotate
        # lowered as shift+shift+or; roll moves excluded as data
        # movement) — a bookkeeping change, not a kernel change.
        "vpu_util_accounting": "v2: 1240 ALU ops/compression "
                               "(968 compressions/file)",
    }))


if __name__ == "__main__":
    main()
