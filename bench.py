"""Round benchmark: batched CAS-ID generation throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload is the FileIdentifierJob hot kernel (SURVEY.md §3.3): for a
batch of large files, hash the 8-byte size prefix + 57,344 sampled bytes
with BLAKE3 and truncate to 16 hex chars
(/root/reference/core/src/object/cas.rs:23-62 semantics). `vs_baseline`
is the speedup over the in-repo vectorized numpy CPU implementation of
the identical algorithm — the measurable stand-in for the reference's CPU
path (the reference publishes no numbers, BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    from spacedrive_tpu.ops import blake3_batch as bb
    from spacedrive_tpu.ops import blake3_jax as bj

    B = 2048
    rng = np.random.default_rng(0)
    payloads = rng.integers(0, 256, size=(B, 57344), dtype=np.uint8)
    sizes = rng.integers(200_000, 50_000_000, size=B).astype(np.uint64)
    words, lengths = bj.build_cas_messages(payloads, sizes)

    # Device path (jit warms on the first call).
    out = bj.blake3_words(words, lengths)
    out.block_until_ready()
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = bj.blake3_words(words, lengths)
    out.block_until_ready()
    device_fps = B * iters / (time.perf_counter() - t0)

    # Correctness spot check against the streaming oracle.
    cas_ids = bj.digests_to_cas_ids(out)
    from spacedrive_tpu.ops.cas import cas_id_of_payload

    for i in (0, B // 2, B - 1):
        expect = cas_id_of_payload(int(sizes[i]), payloads[i].tobytes())
        assert cas_ids[i] == expect, (i, cas_ids[i], expect)

    # CPU baseline: same algorithm, vectorized numpy, smaller batch.
    Bc = 128
    t0 = time.perf_counter()
    bb.blake3_batch(np, words[:Bc], lengths[:Bc])
    cpu_fps = Bc / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "cas_ids_per_sec_large_files",
        "value": round(device_fps, 1),
        "unit": "files/s",
        "vs_baseline": round(device_fps / cpu_fps, 2),
    }))


if __name__ == "__main__":
    main()
