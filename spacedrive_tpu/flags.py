"""Central SDTPU_* environment-flag registry.

Every environment flag the engine reads is DECLARED here — name,
parsed default, parser, and a docstring — and READ through `get()` /
`raw()`. Scattered `os.environ["SDTPU_*"]` reads made the flag surface
unauditable (round-7 review: ~10 literals across six layers, none
discoverable without grep); `tools/sdlint`'s flag-registry pass now
fails the build on any SDTPU_* literal that is not declared here and on
any direct environ read of one outside this module. Writers (benches
and tests toggling a flag via `os.environ[...] = ...` or
`monkeypatch.setenv`) are unaffected — reads go live to the
environment on every call, so toggles keep working mid-process.

Design constraints (same as telemetry.py, which imports this module):
pure stdlib, imports nothing from the package — every layer can import
it without cycles.

README's flag table is generated from this registry
(`python -m tools.sdlint --flag-table`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "Flag", "FLAGS", "declare", "get", "raw", "flag_table_markdown",
    "parse_str", "parse_onoff", "parse_flag1", "parse_float",
    "parse_int", "parse_int_csv",
]


# -- parsers ----------------------------------------------------------------
# Each takes the RAW environment string (never None) and returns the
# typed value; a ValueError falls back to the flag's default, matching
# the defensive parsing every migrated call site already had.

def parse_str(v: str) -> str:
    return v


def parse_onoff(v: str) -> bool:
    """Kill-switch semantics: anything but off/0/false is ON."""
    return v.strip().lower() not in ("off", "0", "false")


def parse_flag1(v: str) -> bool:
    """Opt-in semantics: only 1/on/true/yes enable."""
    return v.strip().lower() in ("1", "on", "true", "yes")


def parse_float(v: str) -> float:
    return float(v)


def parse_int(v: str) -> int:
    return int(v)


def parse_int_csv(v: str) -> List[int]:
    return [int(s) for s in v.split(",") if s.strip()]


@dataclass(frozen=True)
class Flag:
    name: str
    default: Any
    parse: Callable[[str], Any]
    doc: str
    # strict=True: a malformed value RAISES instead of falling back to
    # the default. For flags where a typo silently changing behavior is
    # worse than a crash (fuzz seeds replaying the wrong corpus, a
    # batch budget ignoring the operator) — matches the loud parsing
    # their pre-registry call sites had.
    strict: bool = False


FLAGS: Dict[str, Flag] = {}


def declare(name: str, default: Any, parse: Callable[[str], Any] = parse_str,
            doc: str = "", strict: bool = False) -> Flag:
    if not name.startswith("SDTPU_"):
        raise ValueError(f"flag {name!r} must start with SDTPU_")
    if name in FLAGS:
        raise ValueError(f"flag {name!r} declared twice")
    f = Flag(name, default, parse, doc, strict)
    FLAGS[name] = f
    return f


def raw(name: str) -> Optional[str]:
    """The raw environment string, or None when unset. The flag must be
    declared — an unknown name is a programming error, not a lookup
    miss (that is the whole point of the registry)."""
    if name not in FLAGS:
        raise KeyError(f"undeclared flag {name!r} (declare it in "
                       "spacedrive_tpu/flags.py)")
    return os.environ.get(name)


def get(name: str) -> Any:
    """Parsed value: parser over the live environment, the declared
    default when unset, empty, or unparseable. Reads are NOT cached —
    benches and tests toggle flags mid-process (sync_bench flips
    SDTPU_CLONE_PASSTHROUGH per phase); call sites that need one-shot
    semantics cache on their side (tracing's profiler probe)."""
    flag = FLAGS.get(name)
    if flag is None:
        raise KeyError(f"undeclared flag {name!r} (declare it in "
                       "spacedrive_tpu/flags.py)")
    v = os.environ.get(name)
    if v is None or v == "":
        return flag.default
    try:
        return flag.parse(v)
    except (ValueError, TypeError):
        if flag.strict:
            raise ValueError(
                f"{name}={v!r}: unparseable (see its declaration in "
                f"spacedrive_tpu/flags.py)")
        return flag.default


def flag_table_markdown() -> str:
    """README's generated flag table (one row per declared flag)."""
    out = ["| Flag | Default | Meaning |", "| --- | --- | --- |"]
    for name in sorted(FLAGS):
        f = FLAGS[name]
        default = "unset" if f.default is None else repr(f.default)
        doc = " ".join(f.doc.split())
        out.append(f"| `{name}` | {default} | {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE flag namespace. Keep alphabetical; every entry is enforced by the
# sdlint flag-registry pass (undeclared literals fail the build).
# ---------------------------------------------------------------------------

declare(
    "SDTPU_CHAN_SCALE", 1.0, parse_float,
    "Global multiplier over every declared channel capacity "
    "(channels.py registry; README's generated channel table lists "
    "the per-channel defaults). Read at channel construction, not "
    "per put.")

declare(
    "SDTPU_CHAOS", "", parse_str,
    "Chaos-plane arming spec (chaos.py): `<point>=<fault>[,...];...` "
    "with faults delay:<dur>[:<prob>] or one of error/drop/"
    "disconnect/wedge/corrupt[:<prob>], e.g. "
    "`p2p.tunnel.frame=drop:0.01,delay:50ms`. "
    "Point names must be declared fault points; undeclared names and "
    "kinds a point did not declare are REFUSED at parse. Read at "
    "import / chaos.rearm_from_env(); empty = disarmed (one flag "
    "check per injection site).")

declare(
    "SDTPU_CHAOS_SEED", 0, parse_int,
    "Deterministic RNG seed for the armed chaos plane (chaos.py): "
    "each fault point draws from its own Random seeded (seed, point "
    "name), so a failing storm replays exactly under the same seed "
    "regardless of how concurrent sites interleave.", strict=True)

declare(
    "SDTPU_CLONE_PASSTHROUGH", True, parse_onoff,
    "Kill switch for the full-library-clone blob pass-through fast "
    "path (p2p/sync_net.py). `off` forces the per-op pull loop.")

declare(
    "SDTPU_DEVICE_PIPELINE", "", lambda v: v.strip().lower(),
    "CAS device-pipeline override (ops/staging.py): `force`/`1` always "
    "stage through the accelerator, `off`/`0` always use the host "
    "planes; unset probes the H2D link once per process.")

declare(
    "SDTPU_DISPATCH_LOG", False, lambda v: v == "1",
    "When `1`, every device CAS dispatch appends its batch geometry to "
    "ops/blake3_jax.DISPATCH_LOG (driver/dryrun artifacts read it).")

declare(
    "SDTPU_FLEET_INTERVAL_S", 10.0, parse_float,
    "Seconds between fleet-observatory poll rounds (fleet.py, "
    "supervised under node/fleet): each round pulls every paired "
    "peer's obs.health snapshot into its bounded per-peer ring and "
    "re-merges the fleet view. A peer whose last good snapshot is "
    "older than 2x this interval is marked stale-degraded.")

declare(
    "SDTPU_FUZZ_SEEDS", [7, 23], parse_int_csv,
    "Comma-separated RNG seeds the sync fuzz suite replays "
    "(tests/test_sync_fuzz.py).", strict=True)

declare(
    "SDTPU_H2D_GBPS", None, parse_float,
    "Pin the host→device link-rate probe to a fixed GB/s "
    "(ops/staging.py) instead of measuring — benchmark pinning and "
    "thin-tunnel hosts.")

declare(
    "SDTPU_DONATE_BUFFERS", True, parse_onoff,
    "Kill switch for donated device buffers on the identify pipelines "
    "(ops/overlap.py ring, ops/blake3_jax.py donated CAS dispatch): "
    "donated kernels consume their staged input buffers at dispatch so "
    "each batch's H2D lands in recycled allocator space instead of "
    "growing the in-flight footprint. `off` pins the undonated "
    "programs (the CPU-mesh test suite sets it to dodge a ~45 s "
    "duplicate compile per kernel variant; dedicated donation tests "
    "flip it back with cheap kernels).")

declare(
    "SDTPU_PIPELINE_DEPTH", 3, parse_int,
    "Batches in flight (stage→H2D→kernel→fetch) in the depth-N "
    "identify pipeline (ops/overlap.py). 1 = fully serial; clamped to "
    "the declared `ops.pipeline.*` channel capacity (8). Depth is the "
    "ring-slot count: staged host batches, in-transfer buffers, and "
    "undonated device inputs are all bounded by it.", strict=True)

declare(
    "SDTPU_PIPELINE_DEVICES", 0, parse_int,
    "Cap on local devices the depth-N pipeline round-robins batches "
    "across (ops/overlap.py via parallel/mesh.device_ring). 0 = all "
    "local devices; the CPU-mesh test suite pins 1 so the virtual "
    "8-device mesh doesn't pay a per-device kernel compile.",
    strict=True)

declare(
    "SDTPU_HEALTH_INTERVAL_S", 5.0, parse_float,
    "Seconds between health-observatory sampler ticks (health.py, "
    "supervised under node/health): each tick spools delta-snapshots "
    "of every metric family into the health.series rings, re-"
    "evaluates per-subsystem saturation, and emits a HealthSnapshot "
    "event.")

declare(
    "SDTPU_HEALTH_TOPK", 3, parse_int,
    "Bottleneck-attribution depth of the health observatory "
    "(health.py): the top-k resources driving each non-ok subsystem "
    "state, ranked by severity then evidence score, served by "
    "node.health and rendered by tools/sd_top.py.", strict=True)

declare(
    "SDTPU_INCIDENTS", True, parse_onoff,
    "Incident observatory master switch (incidents.py): when on, "
    "Node bootstrap installs the process-global black box and wires "
    "every detection surface (health states, backoff give-ups, "
    "count-mode sanitizer violations, crash markers) to snapshot-"
    "freeze evidence bundles. `off` makes install() a no-op.")

declare(
    "SDTPU_INCIDENT_DEGRADED_WINDOWS", 3, parse_int,
    "Consecutive health samples a subsystem must hold `degraded` "
    "before the incident observatory opens a health.degraded bundle "
    "(incidents.py) — brief wobbles don't produce postmortems; "
    "`saturated` always fires immediately.", strict=True)

declare(
    "SDTPU_INCIDENT_STORE_MB", 16.0, parse_float,
    "Byte cap (MB) on the on-disk incident-bundle store "
    "(incidents.py): crossing it evicts oldest bundles first and "
    "counts sd_incident_dropped_total. The count cap is the declared "
    "incidents.store channel capacity.")

declare(
    "SDTPU_INCIDENT_WINDOW_S", 60.0, parse_float,
    "Per-fingerprint rate-limit window for incident bundles "
    "(incidents.py): repeat firings of the same subsystem + resource "
    "+ trigger kind inside the window collapse into "
    "sd_incident_deduped_total instead of new bundles.")

declare(
    "SDTPU_FS_AUDIT", "auto", lambda v: v.strip().lower(),
    "Runtime fs auditor (persist.py, armed with the sanitizer): "
    "interposes os.replace/os.fsync, checks the fsync-file -> rename "
    "-> fsync-dir ordering each declared artifact's policy promises, "
    "and flags raw product-module renames outside the persist seam "
    "(persist_undeclared_write / persist_unfsynced_rename — raised "
    "in tier-1, counted in production). `off` skips arming (plain "
    "os.replace/os.fsync, zero overhead); `auto` follows "
    "SDTPU_SANITIZE. Read once at sanitize.install().")

declare(
    "SDTPU_PERSIST_CRASHPOINT", "", parse_str,
    "`<artifact>:<edge>` — SIGKILL this process at the named "
    "declared durability edge inside the persist seam "
    "(persist.crashpoint). How tools/crash_grid.py children die at "
    "every edge of every declared artifact systematically; empty "
    "(default) disables the kill switch.")

declare(
    "SDTPU_LOG_JSON", False, parse_flag1,
    "When on, a JSON-line formatter is installed on the "
    "`spacedrive_tpu` logger (tracing.install_json_logging): every "
    "record carries ts/level/logger/msg plus the CURRENT trace/span "
    "id (the tracing contextvar survives to_thread), so log lines "
    "correlate with node.spans and exported traces.")

declare(
    "SDTPU_LOG_RING", True, parse_onoff,
    "Bounded in-memory log ring (tracing.LogRing, capacity declared "
    "as the tracing.logring channel): installed at Node bootstrap "
    "next to the JSON formatter, it keeps the newest trace/span-"
    "stamped records in-process so incident bundles can freeze a log "
    "tail instead of pointing at unrecoverable stderr.")

declare(
    "SDTPU_PROFILE", None, parse_str,
    "Directory for a jax profiler trace; set → device_span() regions "
    "are captured (tracing.py; probed once per process, "
    "reset_profiler_cache() re-arms).")

declare(
    "SDTPU_RACE_GUARD", "auto", lambda v: v.strip().lower(),
    "Cross-thread race recorder (threadctx.py, armed with the "
    "sanitizer): declared owner classes record (thread id, held "
    "lockset) per attribute write and flag data_race violations. "
    "`off` skips arming (zero overhead); `auto` follows "
    "SDTPU_SANITIZE. Read once at sanitize.install().")

declare(
    "SDTPU_RETRACE_GUARD", "auto", lambda v: v.strip().lower(),
    "jit retrace counter (ops/jit_registry.py, armed with the "
    "sanitizer): `off` disables cache-size accounting and the "
    "per-contract max_traces budget check; `auto` follows "
    "SDTPU_SANITIZE.")

declare(
    "SDTPU_SQL_AUDIT", "auto", lambda v: v.strip().lower(),
    "Runtime SQL auditor (store/sqlaudit.py, armed with the "
    "sanitizer): every executed statement is matched against the "
    "contract registry (store/statements.py) — undeclared statements "
    "and autocommit writes are sanitizer violations (raised in "
    "tier-1, counted in production). `off` skips arming (plain "
    "sqlite3 connections, zero overhead); `auto` follows "
    "SDTPU_SANITIZE. Read once at sanitize.install().")

declare(
    "SDTPU_SQL_EXPLAIN", 0, parse_int,
    "EXPLAIN-sampling period of the runtime SQL auditor: every Nth "
    "execution of a declared read over a registered large table runs "
    "EXPLAIN QUERY PLAN, and full-table scans count into "
    "sd_sql_scan_total{name}. 0 (default) disables sampling.",
    strict=True)

declare(
    "SDTPU_SANITIZE", False, parse_flag1,
    "Opt-in runtime sanitizer (sanitize.py): event-loop stall "
    "detector, lock-order cycle check, write-lock-held-across-await "
    "assertion. Tier-1 runs with it on.")

declare(
    "SDTPU_SANITIZE_MODE", "count", lambda v: v.strip().lower(),
    "`raise` (tests): a detected violation raises at the detection "
    "point; `count` (production): violations only increment "
    "sd_sanitize_* telemetry and record into sanitize.violations().")

declare(
    "SDTPU_SANITIZE_STALL_S", 1.0, parse_float,
    "Event-loop stall threshold in seconds: one callback/task step "
    "hogging the loop longer than this is a sanitizer violation.")

declare(
    "SDTPU_SHARDED_CAS", "auto", lambda v: v.strip().lower(),
    "`off` pins the single-device CAS program even on multi-device "
    "hosts (ops/blake3_jax.py; the CPU-mesh test suite sets it to "
    "dodge a ~50s shard_map compile per batch grid).")

declare(
    "SDTPU_SIM_LINK_GBPS", None, parse_float,
    "Deterministic simulated H2D link for the depth-N pipeline "
    "(ops/overlap.py): every host→device transfer additionally sleeps "
    "nbytes / (rate·1e9) seconds, per device stream, so CPU-only "
    "hosts (tier-1, tools/overlap_bench.py) can pin the overlap math "
    "— measured rate vs the max(stage, h2d, kernel) bound — without "
    "TPU hardware. Unset = real link only.")

declare(
    "SDTPU_SPAN_RING", 512, parse_int,
    "Capacity of the tracing span ring buffer (tracing.py "
    "recent_spans / node.spans / the flight-recorder export). Read "
    "once at import — the ring is module-global; "
    "tracing.configure_span_ring() re-reads it for tests/embedders.",
    strict=True)

declare(
    "SDTPU_STAGE_NATIVE", "auto", lambda v: v.strip().lower(),
    "Packed native staging backend for the device CAS pipeline "
    "(ops/staging.py stage_batch_native → native sd_stage_batch): "
    "`auto`/`on` stage whole batches straight into pooled page-aligned "
    "buffers in the kernel's message layout when libsdio.so is "
    "available; `off` forces the classic stage_files + "
    "build_cas_messages host path with PURE-PYTHON readers (the "
    "classic path's own native pread helpers are pinned off too — "
    "one flag, the whole native staging plane). Fails closed to the "
    "Python path "
    "when the shared object is missing, per-file on bad rows "
    "(ENOENT/EACCES/short read). Read per batch, so benches can A/B "
    "backends mid-process (tools/overlap_bench.py --staging).")

declare(
    "SDTPU_STAGE_POOL_BUFFERS", 0, parse_int,
    "Cap on the staging buffer pool (ops/staging.py StagePool): "
    "pooled page-aligned H2D source pages live-recycled at batch "
    "retirement. 0 = the declared ops.stage.pool channel capacity "
    "(the registry ceiling); a positive value narrows below it — it "
    "never raises it. When the pool is exhausted the batch degrades "
    "to the Python staging path instead of allocating past the bound.",
    strict=True)

declare(
    "SDTPU_STORE_ACTOR", True, parse_onoff,
    "Kill switch for the per-library single-writer group-commit actor "
    "(store/actor.py): `off` degrades Database.write_tx() to the raw "
    "serialized tx() path — one commit per caller, no coalescing — "
    "which is how load_bench measures the before/after write-path "
    "attribution. Read per write_tx entry, so benches can flip it "
    "mid-process.")

declare(
    "SDTPU_STORE_GROUP_LATENCY_S", 0.004, parse_float,
    "Group-commit latency bound of the storage write actor "
    "(store/actor.py): once a group is open, how long the writer "
    "thread waits for more batches to coalesce before committing. "
    "Small = snappier single writers; large = fatter transactions "
    "under storm. The bound is a wait-for-MORE-work budget — a "
    "running batch body never counts against it.")

declare(
    "SDTPU_STORE_GROUP_MAX", 32, parse_int,
    "Group-commit size bound of the storage write actor "
    "(store/actor.py): at most this many queued write batches "
    "coalesce into one fat transaction before the actor commits "
    "(sd_store_group_size records what it actually achieves).",
    strict=True)

declare(
    "SDTPU_STORE_READ_POOL", 4, parse_int,
    "Idle read-only connections the per-library pool keeps warm "
    "(store/db.py): reads borrow a query_only connection instead of "
    "minting one per thread, so concurrent readers stop serializing "
    "on (and stop multiplying) the writer's WAL connection. Borrows "
    "past the cap open a transient connection that closes on "
    "release.", strict=True)

declare(
    "SDTPU_TASK_REAP_S", 5.0, parse_float,
    "Grace period the task supervisor's shutdown reap (tasks.py, "
    "driven by Node.shutdown) waits for cancelled tasks before "
    "declaring them orphaned (a sanitizer violation).")

declare(
    "SDTPU_TELEMETRY", True, parse_onoff,
    "Kill switch for the node-wide metrics registry (telemetry.py): "
    "`off` reduces every increment to one flag check.")

declare(
    "SDTPU_TELEMETRY_INTERVAL", 15.0, parse_float,
    "Seconds between periodic TelemetrySnapshot events on the node "
    "event bus (node.py TelemetryReporter).")

declare(
    "SDTPU_TIMEOUT_SCALE", 1.0, parse_float,
    "Global multiplier over every declared network-await budget "
    "(timeouts.py registry; README's generated timeout table lists "
    "the per-site defaults).")

declare(
    "SDTPU_TRANSFER_GUARD", "auto", lambda v: v.strip().lower(),
    "JAX device-to-host transfer guard inside device_scope()/io() "
    "regions (ops/jit_registry.py, armed with the sanitizer): `auto` "
    "follows SDTPU_SANITIZE_MODE (raise -> disallow, count -> log), "
    "`raise`/`log` force a level, `off` disables.")

declare(
    "SDTPU_VAL_BATCH_BYTES", None, parse_int,
    "Device-validator batch budget in bytes (objects/validator.py); "
    "unset uses the 64 MiB default sized for PCIe/ICI links.",
    strict=True)

declare(
    "SDTPU_WATCHER", "", lambda v: v.strip().lower(),
    "`poll` forces the polling watcher fallback even where inotify is "
    "available (locations/watcher.py; how Linux CI exercises it).")

declare(
    "SDTPU_WIRE_AUDIT", "auto", lambda v: v.strip().lower(),
    "Runtime wire auditor (p2p/wire.py, armed with the sanitizer): "
    "every frame crossing the pack/unpack seam — both tunnel "
    "directions and the stub transports' pack calls — is matched "
    "against its declared message contract; an undeclared kind, a "
    "schema mismatch, a size-cap breach, or a version-const skew is "
    "a `wire_violation` (raised in tier-1, counted in production, "
    "sd_wire_violations_total{kind}). `off` skips arming (pack/"
    "unpack still validate, zero audit overhead); `auto` follows "
    "SDTPU_SANITIZE. Read once at sanitize.install().")
