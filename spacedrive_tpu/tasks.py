"""Structured-concurrency task supervisor: the runtime twin of
tools/sdlint's task-lifecycle pass.

Before this module every background task in the node was a bare
`loop.create_task(...)` with ad-hoc (or missing) shutdown: ~19 spawn
points across p2p/jobs/sync/media/locations, at least one of which
dropped its only task reference (`locations/watcher.py` dirty-scan —
the garbage collector may cancel a task nobody holds). The reference's
job system treats pause/cancel/shutdown as a first-class protocol
(core/src/job/); this module gives the whole async layer the same
machine-checked contract:

- **`spawn(name, coro, owner=...)`** — the ONE way long-lived
  components start background work. Every spawned task lands in a
  process-wide registry keyed by an ownership path (``node#1/p2p/
  discovery``), gets a ``sdtpu:`` task name (so leak tests can sweep
  `asyncio.all_tasks()`), and is watched by a done-callback that
  counts `sd_task_spawned_total{owner}` and records a
  ``task_exception`` sanitizer violation when a task dies with an
  exception nobody awaited (the classic "Task exception was never
  retrieved" black hole, surfaced instead of logged at interpreter
  exit).
- **`reap(owner)`** — cancel-and-gather an ownership subtree, deepest
  owners first (children before parents, so a parent's cleanup still
  has its children stopped). `Node.shutdown()` calls it as the
  backstop AFTER stopping every component: anything still registered
  is cancelled cleanly; anything that survives the grace period
  (`SDTPU_TASK_REAP_S`) is an ORPHAN — counted in
  `sd_task_orphaned_total` and raised as a sanitizer violation in
  tier-1 (`raise` mode), counted in production. Cancel latency per
  task feeds `sd_task_cancel_latency_seconds`.
- **`cancel_and_gather(*tasks)`** — the cancellation-safe stop idiom
  components use instead of the conflated
  ``except (CancelledError, Exception): pass`` shape sdlint's
  cancellation-safety pass now rejects: it swallows only the victims'
  cancellation; our OWN cancellation mid-gather still propagates, and
  a victim's real exception still reaches the supervisor's
  done-callback.

Design constraints (same as flags.py / telemetry.py): stdlib +
telemetry/flags/sanitize only, importable from every layer. The
registry works whether or not the sanitizer is installed — metrics
always count; only the raise/count split follows SDTPU_SANITIZE_MODE.
"""

from __future__ import annotations

import asyncio
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Coroutine, Dict, List, Optional

from . import flags
from .telemetry import TASK_CANCEL_LATENCY, TASK_ORPHANED, TASK_SPAWNED

__all__ = [
    "spawn", "reap", "live", "cancel_and_gather", "unique_owner",
    "owner_label", "TASK_NAME_PREFIX",
]

# asyncio task-name prefix for every supervised task: leak tests sweep
# asyncio.all_tasks() for stragglers bearing it.
TASK_NAME_PREFIX = "sdtpu:"

_OWNER_SEQ_RE = re.compile(r"#\d+")


@dataclass
class TaskRecord:
    name: str
    owner: str
    task: asyncio.Task
    # Stamped by reap() just before .cancel() so the done-callback can
    # observe the task's individual cancel→finished latency.
    cancelled_at: Optional[float] = field(default=None)


# task object → record. Tasks unregister themselves on completion via
# the supervisor's done-callback, so the registry always reflects the
# LIVE set — `live()` after a clean shutdown is empty by construction.
_registry: Dict[asyncio.Task, TaskRecord] = {}
_registry_lock = threading.Lock()
_owner_seq = [0]


def unique_owner(prefix: str) -> str:
    """A process-unique ownership ROOT (``node#3``): two nodes in one
    process (every p2p test) must not reap each other's subtrees."""
    with _registry_lock:
        _owner_seq[0] += 1
        return f"{prefix}#{_owner_seq[0]}"


def owner_label(owner: str) -> str:
    """Telemetry label for an owner path: the per-instance ``#seq``
    uniquifier is stripped so label cardinality stays bounded by the
    component tree, not by how many nodes the process ever created."""
    return _OWNER_SEQ_RE.sub("", owner)


def _in_subtree(owner: str, root: str) -> bool:
    return owner == root or owner.startswith(root + "/")


def _record_violation(kind: str, detail: str, may_raise: bool) -> None:
    from . import sanitize

    sanitize.record(kind, detail, may_raise=may_raise)


def _on_task_done(task: asyncio.Task) -> None:
    with _registry_lock:
        rec = _registry.pop(task, None)
    if rec is None:
        return
    if rec.cancelled_at is not None:
        TASK_CANCEL_LATENCY.observe(time.perf_counter() - rec.cancelled_at)
    if task.cancelled():
        return
    exc = task.exception()  # retrieves it: no destructor log at exit
    if exc is not None:
        _record_violation(
            "task_exception",
            f"supervised task {rec.owner}/{rec.name} died with "
            f"{type(exc).__name__}: {exc}",
            may_raise=False)  # done-callbacks run inside loop internals


def spawn(name: str, coro: Coroutine, owner: str = "proc") -> asyncio.Task:
    """Create a supervised task. Requires a running loop (callers that
    may run loop-less keep their ``except RuntimeError`` guards — the
    coroutine is closed on failure so no 'never awaited' warning
    leaks). The registry holds a strong reference until the task
    finishes, so fire-and-forget spawns cannot be GC-cancelled
    mid-flight (the watcher.py bug this module exists to kill)."""
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        coro.close()
        raise
    task = loop.create_task(  # sdlint: ok[task-lifecycle]
        coro, name=f"{TASK_NAME_PREFIX}{owner}/{name}")
    with _registry_lock:
        _registry[task] = TaskRecord(name=name, owner=owner, task=task)
    TASK_SPAWNED.labels(owner=owner_label(owner)).inc()
    task.add_done_callback(_on_task_done)
    return task


def live(owner: Optional[str] = None) -> List[TaskRecord]:
    """Registered (not yet finished) tasks, optionally restricted to
    an ownership subtree."""
    with _registry_lock:
        recs = list(_registry.values())
    if owner is None:
        return recs
    return [r for r in recs if _in_subtree(r.owner, owner)]


async def cancel_and_gather(*tasks: Optional[asyncio.Task]) -> None:
    """Cancel `tasks` and await their completion — the supervised stop
    idiom. Swallows ONLY the victims' cancellation (gather with
    return_exceptions captures per-task outcomes); if the CALLER is
    cancelled mid-gather that cancellation propagates, and a victim's
    real exception is still recorded by the supervisor's done-callback
    (for raw tasks, gather's retrieval suppresses the exit-time log,
    matching the old per-task ``except`` loops)."""
    victims = [t for t in tasks if t is not None]
    for t in victims:
        t.cancel()
    if victims:
        await asyncio.gather(*victims, return_exceptions=True)


async def reap(owner: str, grace_s: Optional[float] = None) -> List[str]:
    """Cancel-and-gather every registered task under `owner`, deepest
    ownership paths first. Returns the reaped task labels. Tasks still
    pending after `grace_s` (default SDTPU_TASK_REAP_S) are orphans:
    each counts into sd_task_orphaned_total, and one summarizing
    ``task_orphaned`` sanitizer violation raises in tier-1 (`raise`
    mode) AFTER the sweep finishes, so shutdown cleanup still runs."""
    if grace_s is None:
        grace_s = flags.get("SDTPU_TASK_REAP_S")
    reaped: List[str] = []
    orphans: List[TaskRecord] = []
    seen: set = set()
    # Multiple sweeps: a callback queued before shutdown (threadsafe
    # originate_soon, ws-emit, watcher on_dirty) can spawn under this
    # owner WHILE the reap awaits — a single snapshot would let that
    # task outlive the reap uncancelled and unreported.
    for _round in range(3):
        victims = [r for r in live(owner)
                   if not r.task.done() and r.task not in seen]
        if not victims:
            break
        seen.update(r.task for r in victims)
        reaped.extend(f"{r.owner}/{r.name}" for r in victims)
        for depth in sorted({r.owner.count("/") for r in victims},
                            reverse=True):
            layer = [r for r in victims
                     if r.owner.count("/") == depth and not r.task.done()]
            if not layer:
                continue
            start = time.perf_counter()
            # Cancel unconditionally BEFORE the grace-bounded wait:
            # grace_s=0 must still mean "cancel, just don't wait",
            # never "leave everything running".
            for r in layer:
                r.cancelled_at = start
                r.task.cancel()
            pending = {r.task for r in layer}
            while pending:
                remaining = grace_s - (time.perf_counter() - start)
                if remaining <= 0:
                    break
                _done, pending = await asyncio.wait(
                    pending, timeout=min(1.0, remaining))
                # Re-cancel through the grace window (not once): a
                # pre-3.11 deadline() block whose timer races the reap
                # can absorb one cancel into its TimeoutError
                # conversion — a second round reaches the task at its
                # next await, so only a task that truly ignores
                # cancellation is declared an orphan.
                for t in pending:
                    t.cancel()
            orphans.extend(r for r in layer if r.task in pending)
    # Spawns that landed during the final sweep: cancel so they cannot
    # run against the DBs shutdown is about to close, and report them —
    # escaping silently is the one unacceptable outcome.
    stragglers = [r for r in live(owner)
                  if not r.task.done() and r.task not in seen]
    for r in stragglers:
        r.task.cancel()
    orphans.extend(stragglers)
    reaped.extend(f"{r.owner}/{r.name}" for r in stragglers)
    if orphans:
        TASK_ORPHANED.inc(len(orphans))
        _record_violation(
            "task_orphaned",
            "task(s) survived the shutdown reap grace period "
            f"({grace_s}s): "
            + ", ".join(f"{r.owner}/{r.name}" for r in orphans),
            may_raise=True)
    return reaped
