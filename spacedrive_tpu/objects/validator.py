"""ObjectValidatorJob: full-file BLAKE3 integrity checksums.

Mirrors the reference job
(/root/reference/core/src/object/validation/validator_job.rs:78-218 and
validation/hash.rs:10-24): every file_path in the location with
integrity_checksum IS NULL gets a full-file checksum written through sync.

Deviation for throughput: steps are CHUNKed batches (the reference does
one file per step), and each batch hashes files concurrently on a thread
pool with the streaming oracle, or on-device via the chunked grid path
for the "jax" backend (large batches of 1 MiB blocks).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
from typing import Any, Dict, List, Optional, Tuple

from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.paths import IsolatedPath
from ..ops.cas import file_checksum

CHUNK_SIZE = 10


@register_job
class ObjectValidatorJob(StatefulJob):
    NAME = "object_validator"
    IS_BATCHED = True

    def __init__(self, *, location_id: int, sub_path: Optional[str] = None,
                 backend: str = "auto", mode: str = "fill"):
        """mode="fill" (reference semantics, validator_job.rs:78-218):
        checksum every file_path whose integrity_checksum IS NULL.
        mode="verify" (net-new): re-hash files that ALREADY have a
        checksum and report mismatches — bit-rot/corruption detection,
        which the reference never does."""
        if mode not in ("fill", "verify"):
            raise ValueError(f"unknown validator mode {mode!r}")
        super().__init__(location_id=location_id, sub_path=sub_path,
                         backend=backend, mode=mode)
        self.location_id = location_id
        self.sub_path = sub_path
        self.backend = backend
        self.mode = mode

    def _init_sync(self, ctx: JobContext):
        """Cursor-paginated steps (same shape as the identifier): the
        resumable state is (WHERE, cursor, counters) — O(1) regardless
        of scan size. The old design serialized every pending row into
        the step list, which made the 3 s crash checkpoint re-msgpack
        ~100 MB of state at 1M files."""
        db = ctx.db
        from ..locations.file_path_helper import job_prologue
        checksum_filter = ("integrity_checksum IS NULL"
                           if self.mode == "fill"
                           else "integrity_checksum IS NOT NULL")
        loc, where, params = job_prologue(
            db, self.location_id, self.sub_path,
            f"location_id = ? AND is_dir = 0 AND {checksum_filter}",
            [self.location_id])
        # binds the declared identifier.orphan_count shape
        count = db.query_one(
            f"SELECT COUNT(*) AS n FROM file_path WHERE {where}",
            params)["n"]
        if count == 0:
            raise EarlyFinish("nothing to validate")
        chunk = CHUNK_SIZE
        from .. import native
        if (self.backend in ("auto", "native", "jax")
                and native.available() and count >= 4096):
            # Big scans on the batched planes step in large chunks so the
            # per-step orchestration amortizes (identifier rationale).
            chunk = 2048
        data = {"location_path": loc["path"], "where": where,
                "params": list(params), "cursor": 0, "chunk": chunk,
                "validated": 0, "mismatched": 0}
        steps = [{} for _ in range(-(-count // chunk))]
        ctx.progress(task_count=len(steps))
        return data, steps

    async def execute_step(self, ctx, data, step, step_number):
        outcome = await asyncio.to_thread(self._step, ctx, data, step)
        # IntegrityViolation events are collected by the worker-thread
        # step body and emitted HERE, back on the event loop: EventBus
        # fan-out is loop-affine (sdlint thread-boundary), and the
        # relay costs nothing — the step has to return before the next
        # one dispatches anyway.
        events = (outcome.metadata.pop("_integrity_events", [])
                  if outcome.metadata else [])
        if events:
            node = ctx.services.get("node")
            if node is not None:
                for ev in events:
                    node.events.emit(ev)
        return outcome

    def _fetch_rows(self, db, data) -> List[Dict[str, Any]]:
        # binds the declared validator.page shape
        rows = db.query(
            f"SELECT id, pub_id, materialized_path, name, extension, "
            f"integrity_checksum, size_in_bytes_bytes "
            f"FROM file_path WHERE {data['where']} "
            f"AND id >= ? ORDER BY id LIMIT ?",
            list(data["params"]) + [data["cursor"], data["chunk"]])
        return [{
            "id": r["id"], "pub_id": r["pub_id"],
            "materialized_path": r["materialized_path"],
            "name": r["name"] or "", "extension": r["extension"] or "",
            "size": int.from_bytes(r["size_in_bytes_bytes"] or b"", "big"),
            "expected": r["integrity_checksum"],
        } for r in rows]

    # Files at or under this size batch-hash MANY per device dispatch
    # (amortizing the tunnel's ~28 ms per-dispatch latency — VERDICT r4
    # item 4); larger files stream through sequence-sharded windows.
    SMALL_FILE_CAP = 4 << 20
    # Padded grid bytes per batched dispatch. 64 MiB suits a local
    # PCIe/ICI-attached chip; on thin links (the tunneled bench chip
    # moves 10-20 MB/s on bad days) dispatches must stay in the
    # few-second range or the remote worker stalls — override with
    # SDTPU_VAL_BATCH_BYTES.
    BATCH_BYTES = 64 << 20
    BATCH_ROWS = 512

    @property
    def batch_bytes(self) -> int:
        from .. import flags

        env = flags.get("SDTPU_VAL_BATCH_BYTES")
        return env if env else self.BATCH_BYTES

    def _checksums_jax(self, jobs, errors):
        """Device checksums, two regimes:

        - small files: sorted by size (tight shared chunk grid) and
          packed into ONE batched dispatch per ~BATCH_BYTES page via
          checksums_words_batched — the page pays the host↔device RPC
          latency once instead of once per file;
        - large files: each streamed through mesh-window sequence
          sharding (bounded memory at any size, ops/seqhash.py)."""
        import os as _os

        import jax

        from ..ops import jit_registry
        from ..ops.blake3_jax import checksums_words_batched
        from ..ops.seqhash import sharded_file_checksum
        from ..parallel.mesh import batch_mesh

        small, big = [], []
        for r, path in jobs:
            try:
                sz = _os.path.getsize(path)
            except OSError as e:
                errors.append(f"{path}: {e}")
                continue
            (small if sz <= self.SMALL_FILE_CAP else big).append(
                (r, path, sz))

        small.sort(key=lambda t: t[2])

        def _padded_row(sz: int) -> int:
            # the dispatch grid pads every row to the batch's pow2 max
            # chunk count — ascending size order means the CURRENT file
            # sets that max, so charge its padded cost to the budget
            chunks = max(1, -(-max(sz, 1) // 1024))
            return (1 << (chunks - 1).bit_length()) * 1024

        i = 0
        while i < len(small):
            batch, blobs = [], []
            while i < len(small) and len(batch) < self.BATCH_ROWS:
                r, path, sz = small[i]
                # Budget the PADDED grid, not the raw payload: one 4 MiB
                # file after 500 tiny ones would otherwise balloon the
                # dispatch to rows × pow2(max) ≈ GiBs of zeros.
                if batch and (len(batch) + 1) * _padded_row(sz) \
                        > self.batch_bytes:
                    break
                i += 1
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError as e:
                    errors.append(f"{path}: {e}")
                    continue
                blobs.append(data)
                batch.append((r, path))
            if blobs:
                # Guarded dispatch (round 10): the page's only
                # sanctioned fetch is checksums_words_batched's
                # declared io("cas.checksums") — a stray D2H here
                # raises under the tier-1 sanitizer.
                with jit_registry.device_scope("validator.batched"):
                    hexes = checksums_words_batched(blobs)
                for (r, path), hx in zip(batch, hexes):
                    yield r, path, hx

        if not big:
            return
        # Streaming windows need a power-of-two device count (subtree
        # alignment); on e.g. a 6- or 12-device mesh use the largest
        # power-of-two subset instead of erroring on every file.
        # batch_mesh is cached per device tuple, so this per-step call
        # returns the SAME Mesh object every step — seqhash's
        # _sharded_reduce keys its trace cache on the mesh static arg,
        # and a fresh mesh per step would risk a retrace per step
        # (jit-registry contract seqhash.reduce).
        devices = list(jax.devices())
        pow2 = 1 << (len(devices).bit_length() - 1)
        mesh = batch_mesh(devices[:pow2])
        D = int(mesh.devices.size)
        shard_chunks = max(64, (8 << 20) // (D * 1024))
        # power-of-two shard size for subtree alignment
        shard_chunks = 1 << (shard_chunks - 1).bit_length()
        for r, path, _sz in big:
            try:
                yield r, path, sharded_file_checksum(
                    mesh, path, shard_chunks=shard_chunks)
            except (OSError, ValueError) as e:
                errors.append(f"{path}: {e}")

    def _step(self, ctx: JobContext, data, step) -> StepOutcome:
        db, sync = ctx.db, ctx.library.sync
        loc_path = data["location_path"]
        rows = self._fetch_rows(db, data)
        if not rows:
            return StepOutcome()
        # Advance past this page only once it is fully processed (end of
        # this method) — an interrupted step replays the same page, and
        # the guarded UPDATE keeps the replay idempotent.
        next_cursor = rows[-1]["id"] + 1
        jobs: List[Tuple[dict, str]] = []
        for r in rows:
            iso = IsolatedPath.from_db_row(
                self.location_id, False, r["materialized_path"],
                r["name"], r["extension"])
            jobs.append((r, iso.join_on(loc_path)))

        errors: List[str] = []
        results: List[Tuple[dict, str, str]] = []  # (row, path, checksum)

        from .. import native
        if self.backend == "jax" and jobs:
            # Device plane: each file's chunk chain is sequence-sharded
            # across the mesh and streamed in windows (ops/seqhash.py
            # StreamingShardedChecksum) — bounded memory at any file
            # size, oracle-exact. Explicit opt-in: on slow host→device
            # links the native streamer wins (ops/staging.py policy).
            for r, path, checksum in self._checksums_jax(jobs, errors):
                results.append((r, path, checksum))
        elif native.available() and jobs:
            # Batched native plane: one call, pooled pread + C++ BLAKE3.
            # DB sizes route small files to the cross-file SIMD groups
            # without a stat sweep (partition hint only — stale sizes
            # re-route at read time, never change a digest).
            import numpy as np
            hexes, status = native.checksum_files(
                [p for _, p in jobs],
                sizes_hint=np.array([r["size"] for r, _ in jobs],
                                    dtype=np.uint64))
            for (r, path), checksum, st in zip(jobs, hexes, status):
                if checksum is None:
                    errors.append(
                        f"{path}: "
                        f"{native.STATUS_MESSAGES.get(int(st), 'error')}")
                else:
                    results.append((r, path, checksum))
        else:
            def one(r, path):
                return r, path, file_checksum(path)

            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=CHUNK_SIZE) as pool:
                futs = [pool.submit(one, r, p) for r, p in jobs]
                for fut in futs:
                    try:
                        results.append(fut.result())
                    except OSError as e:
                        errors.append(str(e))

        if self.mode == "verify":
            # Net-new corruption pass: compare against the stored
            # checksum; mismatches are non-fatal errors + events, never
            # silently "repaired" (the stored value is the evidence).
            # This body runs in a to_thread worker: EventBus emit is
            # loop-affine, so violations ride the outcome metadata and
            # execute_step emits them after the hop back to the loop.
            integrity_events = []
            for r, path, checksum in results:
                if checksum != r.get("expected"):
                    data["mismatched"] += 1
                    errors.append(
                        f"CHECKSUM MISMATCH {path}: stored "
                        f"{r.get('expected')}, current {checksum}")
                    integrity_events.append({
                        "type": "IntegrityViolation",
                        "file_path_id": r["id"], "path": path,
                    })
            data["validated"] += len(results)
            data["cursor"] = next_cursor
            ctx.progress(message=(
                f"verified {data['validated']} files, "
                f"{data['mismatched']} mismatches"))
            return StepOutcome(errors=errors, metadata={
                "validated": data["validated"],
                "mismatched": data["mismatched"],
                "_integrity_events": integrity_events})

        with db.write_tx() as conn:
            db.run_many(
                "validator.fill_checksum",
                [(checksum, r["id"]) for r, _p, checksum in results],
                conn=conn)
            n_ops = sync.bulk_shared_ops(conn, "file_path", [
                (r["pub_id"], "u:integrity_checksum", "integrity_checksum",
                 checksum, None) for r, _p, checksum in results])
        if n_ops:
            sync._notify_created()
        data["validated"] += len(results)
        data["cursor"] = next_cursor
        ctx.progress(message=f"validated {data['validated']} files")
        return StepOutcome(errors=errors,
                           metadata={"validated": data["validated"]})

    async def finalize(self, ctx, data, metadata):
        return metadata
