"""Filesystem operation jobs: copy, cut, delete, erase.

Behavioral equivalents of the reference's fs jobs
(/root/reference/core/src/object/fs/{mod,copy,cut,delete,erase}.rs):

- copy: per-file steps; directories expand into child steps at execution
  time (copy.rs:100-170); name collisions resolve via " (N)" suffix
  dedup (mod.rs:157-218, DUPLICATE_PATTERN " \\(\\d+\\)" mod.rs:36).
- cut: rename within/between locations, falling back to copy+delete
  across devices (cut.rs semantics — "file in use" errors are non-fatal).
- delete: remove file or whole dir tree (delete.rs:34).
- erase: overwrite file bytes with `passes` rounds of random data before
  unlinking (erase.rs:60-160 driving sd-crypto's erase); directories
  expand to children then are removed in finalize.

Steps are plain dicts (msgpack-serializable) resolved from file_path ids
at init, so paused jobs survive process death like every StatefulJob.
"""

from __future__ import annotations

import asyncio
import os
import re
import secrets
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.file_path_helper import load_location
from ..locations.paths import IsolatedPath

DUPLICATE_PATTERN = re.compile(r" \(\d+\)")  # fs/mod.rs:36
ERASE_BLOCK = 1_048_576


class FsJobError(Exception):
    pass


def construct_target_filename(name: str, extension: str, is_dir: bool) -> str:
    """fs/mod.rs:135-155."""
    if is_dir or not extension:
        return name
    return f"{name}.{extension}"


def append_digit_to_filename(file_name: str, ext: Optional[str],
                             current_int: int) -> str:
    """fs/mod.rs:157-175: strip a trailing ' (N)' then append ' (i)'.
    Only a suffix match is stripped — 'report (1) final' keeps its
    mid-name ' (1)'."""
    matches = list(DUPLICATE_PATTERN.finditer(file_name))
    base = file_name
    if matches and matches[-1].end() == len(file_name):
        base = file_name[:matches[-1].start()]
    if ext:
        return f"{base} ({current_int}).{ext}"
    return f"{base} ({current_int})"


def find_available_filename_for_duplicate(target_path: str) -> str:
    """First ' (N)' variant that doesn't exist (fs/mod.rs:177-218)."""
    parent = os.path.dirname(target_path)
    base = os.path.basename(target_path)
    dot = base.rfind(".")
    if dot > 0:
        stem, ext = base[:dot], base[dot + 1:]
    else:
        stem, ext = base, None
    for i in range(1, 1 << 16):
        candidate = os.path.join(
            parent, append_digit_to_filename(stem, ext, i))
        if not os.path.exists(candidate):
            return candidate
    raise FsJobError(f"failed to find available name for {target_path}")


def _file_datas(db, location_id: int, location_path: str,
                file_path_ids: List[int]) -> List[Dict[str, Any]]:
    """get_many_files_datas (fs/mod.rs:53-87): resolve ids → full paths."""
    out = []
    for fid in file_path_ids:
        row = db.run("api.file_path.by_id", (fid,))
        if row is None:
            raise FsJobError(f"file_path {fid} not found")
        iso = IsolatedPath.from_db_row(
            location_id, bool(row["is_dir"]), row["materialized_path"],
            row["name"] or "", row["extension"] or "")
        out.append({
            "id": row["id"], "pub_id": row["pub_id"],
            "is_dir": bool(row["is_dir"]),
            "name": row["name"] or "", "extension": row["extension"] or "",
            "full_path": iso.join_on(location_path),
        })
    return out


def _child_step(db, location_id: int, location_path: str, child_path: str,
                is_dir: bool) -> Optional[Dict[str, Any]]:
    """Resolve a directory child into a step via its DB row; unindexed
    children are skipped by copy (copy.rs:152-159) but still processed by
    delete/erase paths via raw fs operations."""
    try:
        iso = IsolatedPath.new(location_id, location_path, child_path, is_dir)
    except ValueError:
        return None
    row = db.run("indexer.path_by_key", iso.db_key())
    if row is None:
        return None
    return {
        "id": row["id"], "pub_id": row["pub_id"],
        "is_dir": bool(row["is_dir"]),
        "name": row["name"] or "", "extension": row["extension"] or "",
        "full_path": child_path,
    }


class _FsJobBase(StatefulJob):
    """Common init: resolve location + file datas into steps."""

    def __init__(self, *, location_id: int, file_path_ids: List[int],
                 **extra: Any):
        super().__init__(location_id=location_id,
                         file_path_ids=list(file_path_ids), **extra)
        self.location_id = location_id
        self.file_path_ids = list(file_path_ids)

    def _location_path(self, ctx: JobContext) -> str:
        return load_location(ctx.db, self.location_id)["path"]


@register_job
class FileDeleterJob(_FsJobBase):
    NAME = "file_deleter"  # delete.rs:34

    async def init(self, ctx: JobContext):
        path = await asyncio.to_thread(self._location_path, ctx)
        steps = await asyncio.to_thread(
            _file_datas, ctx.db, self.location_id, path,
            self.file_path_ids)
        if not steps:
            raise EarlyFinish("nothing to delete")
        return {"location_path": path}, steps

    async def execute_step(self, ctx, data, step, step_number):
        def run():
            # Idempotent: a replayed step whose target already vanished is
            # a no-op (steps replay after pause/crash, jobs/job.py).
            full = step["full_path"]
            if step["is_dir"]:
                shutil.rmtree(full, ignore_errors=True)
            elif os.path.lexists(full):
                os.remove(full)
        await asyncio.to_thread(run)
        return StepOutcome()


@register_job
class FileEraserJob(_FsJobBase):
    NAME = "file_eraser"  # erase.rs:63

    def __init__(self, *, location_id: int, file_path_ids: List[int],
                 passes: int = 1):
        super().__init__(location_id=location_id,
                         file_path_ids=file_path_ids, passes=passes)
        self.passes = passes

    async def init(self, ctx: JobContext):
        path = await asyncio.to_thread(self._location_path, ctx)
        steps = await asyncio.to_thread(
            _file_datas, ctx.db, self.location_id, path,
            self.file_path_ids)
        if not steps:
            raise EarlyFinish("nothing to erase")
        return {"location_path": path, "dirs_to_remove": []}, steps

    def _expand_dir(self, ctx: JobContext, data, step) -> list:
        # Expand children as further steps; dir removed in finalize
        # (erase.rs:99-137). Unindexed children MUST still be erased —
        # skipping them would delete plaintext bytes unscrubbed — so
        # they get synthetic steps without DB rows.
        more = []
        for entry in os.scandir(step["full_path"]):
            if entry.is_symlink():
                # NEVER scrub through a symlink — the target may live
                # outside the erase scope. Remove just the link.
                os.remove(entry.path)
                continue
            is_dir = entry.is_dir(follow_symlinks=False)
            child = _child_step(
                ctx.db, self.location_id, data["location_path"],
                entry.path, is_dir)
            if child is None:
                child = {"id": None, "pub_id": None, "is_dir": is_dir,
                         "name": entry.name, "extension": "",
                         "full_path": entry.path}
            more.append(child)
        return more

    async def execute_step(self, ctx, data, step, step_number):
        if step["is_dir"]:
            more = await asyncio.to_thread(self._expand_dir, ctx, data, step)
            data["dirs_to_remove"].append(step["full_path"])
            return StepOutcome(more_steps=more)

        def erase():
            full = step["full_path"]
            if os.path.islink(full):
                os.remove(full)
                return
            if not os.path.exists(full):
                return  # replayed step: already erased
            from .. import native
            if native.available():
                native.secure_erase(full, passes=max(1, self.passes))
                os.remove(full)
                return
            size = os.path.getsize(full)
            # In-place overwrite is the POINT (secure erase); the
            # Python fallback mirrors native.secure_erase above.
            # sdlint: ok[io-durability]
            with open(full, "r+b") as f:
                for _ in range(max(1, self.passes)):
                    f.seek(0)
                    remaining = size
                    while remaining > 0:
                        n = min(ERASE_BLOCK, remaining)
                        f.write(secrets.token_bytes(n))
                        remaining -= n
                    f.flush()
                    os.fsync(f.fileno())
                f.truncate(0)
            os.remove(full)
        await asyncio.to_thread(erase)
        return StepOutcome(metadata={"erased": step["full_path"]})

    async def finalize(self, ctx, data, metadata):
        def sweep():
            # Deepest-first so nested dirs go before their parents.
            for d in sorted(data["dirs_to_remove"], key=len, reverse=True):
                try:
                    os.rmdir(d)
                except OSError:
                    shutil.rmtree(d, ignore_errors=True)
        await asyncio.to_thread(sweep)
        return metadata


class _CopyBase(_FsJobBase):
    """Shared copy machinery for copy and the cross-device cut fallback."""

    def __init__(self, *, location_id: int, file_path_ids: List[int],
                 target_location_id: int,
                 target_relative_directory: str = "", **extra: Any):
        super().__init__(
            location_id=location_id, file_path_ids=file_path_ids,
            target_location_id=target_location_id,
            target_relative_directory=target_relative_directory, **extra)
        self.target_location_id = target_location_id
        self.target_relative_directory = target_relative_directory

    def _init_sync(self, ctx: JobContext):
        db = ctx.db
        src_path = self._location_path(ctx)
        tgt_loc = load_location(db, self.target_location_id)
        tgt_base = os.path.join(
            tgt_loc["path"],
            self.target_relative_directory.strip("/").replace("/", os.sep))
        steps = []
        for fd in _file_datas(db, self.location_id, src_path,
                              self.file_path_ids):
            target = os.path.join(tgt_base, construct_target_filename(
                fd["name"], fd["extension"], fd["is_dir"]))
            fd["target_full_path"] = target
            steps.append(fd)
        if not steps:
            raise EarlyFinish("nothing to copy")
        return {"sources_location_path": src_path}, steps


@register_job
class FileCopierJob(_CopyBase):
    NAME = "file_copier"  # copy.rs:55

    async def execute_step(self, ctx, data, step, step_number):
        return await asyncio.to_thread(self._copy_one, ctx, data, step)

    def _copy_one(self, ctx: JobContext, data, step) -> StepOutcome:
        src, target = step["full_path"], step["target_full_path"]
        if step["is_dir"]:
            # Existing target dirs MERGE (children dedup individually) —
            # matching the reference's create_dir_all with no dir-level
            # " (N)" dedup (copy.rs:117-120,152).
            os.makedirs(target, exist_ok=True)
            more = []
            for entry in os.scandir(src):
                child = _child_step(
                    ctx.db, self.location_id, data["sources_location_path"],
                    entry.path, entry.is_dir(follow_symlinks=False))
                if child is None:
                    continue  # not indexed → skipped (copy.rs:152-159)
                child["target_full_path"] = os.path.join(
                    target, os.path.relpath(entry.path, src))
                more.append(child)
            return StepOutcome(more_steps=more)
        if os.path.exists(target):
            same_file = False
            try:
                same_file = os.path.samefile(src, target)
            except OSError:
                pass
            if not same_file:
                # Replay detection (idempotent steps): an interrupted-
                # then-replayed copy finds its own completed output —
                # identical size+mtime — and must not spawn a ' (N)'
                # duplicate. (duplicateFiles into the same dir hits the
                # samefile branch above and always dedup-names.)
                try:
                    import filecmp
                    if filecmp.cmp(src, target, shallow=True):
                        return StepOutcome()
                except OSError:
                    pass
            try:
                target = find_available_filename_for_duplicate(target)
            except FsJobError as e:
                return StepOutcome(errors=[str(e)])
        os.makedirs(os.path.dirname(target), exist_ok=True)
        shutil.copy2(src, target)
        return StepOutcome()


@register_job
class FileCutterJob(_CopyBase):
    NAME = "file_cutter"  # cut.rs:43

    async def execute_step(self, ctx, data, step, step_number):
        def run() -> StepOutcome:
            src, target = step["full_path"], step["target_full_path"]
            if os.path.normpath(src) == os.path.normpath(target):
                return StepOutcome(
                    errors=[f"source and target are the same: {src}"])
            if not os.path.lexists(src):
                if os.path.exists(target):
                    return StepOutcome()  # replayed step: move completed
                return StepOutcome(errors=[f"source missing: {src}"])
            if os.path.exists(target):
                target2 = find_available_filename_for_duplicate(target)
            else:
                target2 = target
            os.makedirs(os.path.dirname(target2), exist_ok=True)
            try:
                # User-file MOVE (the cut job relocates the user's
                # bytes), not an artifact commit; cross-device falls
                # back to copy+delete.
                # sdlint: ok[io-durability]
                os.rename(src, target2)
            except OSError:
                # Cross-device: copy then delete.
                if step["is_dir"]:
                    shutil.copytree(src, target2)
                    shutil.rmtree(src)
                else:
                    shutil.copy2(src, target2)
                    os.remove(src)
            return StepOutcome()
        return await asyncio.to_thread(run)
