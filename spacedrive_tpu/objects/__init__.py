from .identifier import FileIdentifierJob
from .validator import ObjectValidatorJob
from . import fs_ops  # noqa: F401 — registers copy/cut/delete/erase jobs
from . import crypto_ops  # noqa: F401 — registers encrypt/decrypt jobs

__all__ = ["FileIdentifierJob", "ObjectValidatorJob"]
