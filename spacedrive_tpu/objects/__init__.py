from .identifier import FileIdentifierJob
from .validator import ObjectValidatorJob

__all__ = ["FileIdentifierJob", "ObjectValidatorJob"]
