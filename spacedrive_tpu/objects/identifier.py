"""FileIdentifierJob: CAS-ID every orphan file_path, link/create objects.

The flagship hot path (SURVEY.md §3.3). Behavior mirrors the reference job
(/root/reference/core/src/object/file_identifier/file_identifier_job.rs:72-309
and mod.rs:100-331): cursor-paginated chunks of CHUNK_SIZE orphans
(object_id IS NULL, is_dir = 0), per chunk: compute kind + CAS ID, write
cas_ids via sync, link file_paths to existing objects matching by cas_id,
create objects for the rest.

TPU-first deviations:
- the per-chunk hashing is a *batched* staged pipeline
  (ops/staging.cas_ids_for_files) on the configured backend
  ("oracle" | "numpy" | "jax" | "auto") instead of per-file streaming;
- files in one chunk sharing a cas_id share ONE new object (the reference
  creates an object per file_path and only dedups against earlier chunks).
"""

from __future__ import annotations

import asyncio
import os
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..sync.crdt import OpKind, uuid4_bytes_batch

from ..files import resolve_kind
from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.file_path_helper import materialized_like, sub_path_children_mat
from ..locations.paths import IsolatedPath
from ..ops import jit_registry, staging
from ..ops.staging import cas_ids_for_files
from ..telemetry import IDENT_FILES, IDENT_PHASE_SECONDS

CHUNK_SIZE = 100  # file_identifier/mod.rs:36

# The identifier's one op per identified file: cas_id + object link
# together, per-field LWW on apply (sync/crdt.py OpKind.multi_update).
LINK_KIND = OpKind.multi_update(("cas_id", "object_id"))


def _usable_cpus() -> int:
    """CPUs this process may actually run on (Linux affinity masks and
    container quotas make os.cpu_count() a lie in pods)."""
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def orphan_filters(location_id: int, cursor: int,
                   sub_mat_path: Optional[str]) -> Tuple[str, list]:
    """WHERE clause for orphan file_paths
    (orphan_path_filters, file_identifier_job.rs:245-270)."""
    where = ("object_id IS NULL AND is_dir = 0 AND location_id = ? "
             "AND id >= ?")
    params: list = [location_id, cursor]
    where = materialized_like(where, params, sub_mat_path)
    return where, params


def _in_chunks(seq: List, n: int = 900):
    for i in range(0, len(seq), n):
        yield seq[i:i + n]


def stage_file_list(rows: List[Dict[str, Any]], location_id: int,
                    location_path: str) -> List[Tuple[str, int]]:
    """Orphan rows → (absolute path, size) pairs for the staged hasher.

    Inlines IsolatedPath.from_db_row().join_on() — same string algebra
    (paths.py:112-154), minus one dataclass per file; ~4 µs/file
    matters at 1M. The sep check keeps non-POSIX parity."""
    files: List[Tuple[str, int]] = []
    base = os.fspath(location_path)
    sep_fix = os.sep != "/"
    # rel never starts with a separator (materialized_path[1:] strips
    # the leading "/"), so join(base, rel) is exactly base+sep+rel —
    # os.path.join's per-call scan was ~0.9 s of a 200k identify.
    if not base.endswith(os.sep):
        base += os.sep
    for r in rows:
        name = r["name"] or ""
        ext = r["extension"] or ""
        rel = (f"{r['materialized_path'][1:]}{name}.{ext}" if ext
               else f"{r['materialized_path'][1:]}{name}")
        if sep_fix:
            rel = rel.replace("/", os.sep)
        size = int.from_bytes(r["size_in_bytes_bytes"] or b"", "big")
        files.append((base + rel, size))
    return files


@dataclass
class TxBatch:
    """Per-transaction bookkeeping when the job batches several chunks
    into one commit (identify_chunk's `conn` mode): cas-map keys added
    inside the open transaction (popped back out if it rolls back) and
    the op count whose created-broadcast waits for the commit."""

    cas_added: List[str] = field(default_factory=list)
    n_ops: int = 0


def identify_chunk(library, location_id: int, location_path: str,
                   rows: List[Dict[str, Any]], backend: str = "auto",
                   timings: Optional[Dict[str, float]] = None,
                   prehashed: Optional[Tuple] = None,
                   cas_map: Optional[Dict[str, Tuple[int, bytes]]] = None,
                   conn=None, batch: Optional[TxBatch] = None,
                   ) -> Tuple[int, int, List[str]]:
    """The identifier's per-chunk kernel (identifier_job_step,
    mod.rs:100-331): batched CAS hashing, cas_id writes, object
    linking/creation — all through sync. Returns (linked, created,
    errors). Shared by the job and the shallow/watcher path.

    Without `conn`, all writes land in ONE transaction per chunk (the
    reference batches per pass, mod.rs:144/167/231; one atomic chunk is
    strictly tighter and 3× fewer commits), with executemany for the
    row loops so Python stays out of the per-file statement path. With
    `conn` + `batch`, the caller owns a transaction spanning SEVERAL
    chunks (FileIdentifierJob commit batching — WAL commit overhead
    amortizes across a step): domain+op writes land on that connection,
    cas-map additions are recorded in `batch.cas_added` so the caller
    can roll them back, and the created-broadcast is deferred to
    `batch.n_ops` until the caller commits. `timings` (optional)
    accumulates per-phase seconds: prep / hash / db / ops.

    `prehashed` = (files, ids, read_errors) from the job's hash-ahead
    pipeline (chunk i+1 staged+hashed in a worker thread while chunk
    i's transaction commits — CPU overlapping the fsync wait).

    `cas_map` (job-lifetime; updated as each chunk's writes land, keyed
    back out on rollback) trades the per-chunk in-tx probes for memory.
    Concurrency note: an object committed by ANOTHER writer (watcher
    shallow-identify, sync ingest) mid-run is invisible to the map, so
    the same content can transiently get a second object row — the
    dedup job collapses those, and the reference is strictly more
    duplicative (it creates an object per file_path within a chunk,
    mod.rs:231-331).
    """
    t = timings if timings is not None else {}

    def _mark(phase: str, t0: float) -> float:
        t1 = time.perf_counter()
        t[phase] = t.get(phase, 0.0) + (t1 - t0)
        return t1

    db, sync = library.db, library.sync
    tp = time.perf_counter()
    if prehashed is not None:
        files, ids, read_errors = prehashed
        tp = _mark("prep", tp)
    else:
        files = stage_file_list(rows, location_id, location_path)
        tp = _mark("prep", tp)

        # ---- batched hashing (the TPU-fed kernel) ----
        ids, read_errors = cas_ids_for_files(files, backend=backend)
        tp = _mark("hash", tp)
    kinds = {
        i: int(resolve_kind(files[i][0], ext=rows[i]["extension"] or ""))
        for i in ids
    }
    tp = _mark("prep", tp)

    linked = created = n_ops = 0
    own_tx = conn is None
    with (db.write_tx() if own_tx else nullcontext(conn)) as conn:
        # ---- link targets: existing objects by cas_id (mod.rs:167-225).
        # With a preloaded cas_map (the job's whole-library dict,
        # maintained across chunks) the per-chunk IN() probes vanish —
        # ~15% of the 1M wall. Without one, query as before.
        if cas_map is not None:
            existing = cas_map
        else:
            cas_list = sorted({c for c in ids.values() if c})
            existing = {}
            for chunk in _in_chunks(cas_list):
                ph = ",".join("?" for _ in chunk)
                # binds the declared identifier.cas_links shape
                for r in conn.execute(
                    f"SELECT fp.cas_id AS cas_id, o.id AS oid, "
                    f"o.pub_id AS opub "
                    f"FROM file_path fp JOIN object o ON o.id = fp.object_id "
                    f"WHERE fp.cas_id IN ({ph})", chunk):
                    existing.setdefault(r["cas_id"], (r["oid"], r["opub"]))
        tp = _mark("db_link", tp)

        # ---- resolve every row to an object: link or create ------------
        by_cas: Dict[str, bytes] = {}
        pub_of: Dict[int, bytes] = {}
        new_objects: List[Tuple[bytes, int, Any]] = []
        create_specs: List[Tuple] = []
        oid_of: Dict[bytes, int] = {}
        fresh_pubs = uuid4_bytes_batch(len(ids))  # one urandom syscall
        for i, cas_id in ids.items():
            hit = existing.get(cas_id) if cas_id is not None else None
            if hit is not None:
                oid_of[hit[1]] = hit[0]
                pub_of[i] = hit[1]
                linked += 1
            elif cas_id is not None and cas_id in by_cas:
                pub_of[i] = by_cas[cas_id]  # same-chunk duplicate
            else:
                opub = fresh_pubs[len(new_objects)]
                date_created = rows[i]["date_created"]
                new_objects.append((opub, kinds[i], date_created))
                create_specs.append((opub, "c", None, None, {
                    "kind": kinds[i], "date_created": date_created}))
                if cas_id is not None:
                    by_cas[cas_id] = opub
                pub_of[i] = opub
        tp = _mark("ops", tp)

        # ---- domain writes: objects + ONE file_path update pass --------
        library.db.run_many("identifier.object_insert", new_objects,
                            conn=conn)
        created = len(new_objects)
        if new_objects:
            # Consecutive rowids: inside one tx each rowid-table insert
            # gets max(rowid)+1 and we hold the write lock, so the batch
            # occupies [last-n+1, last] in insertion order — no SELECT-
            # back of n rows. One probe guards the assumption.
            last = library.db.run("store.last_rowid", conn=conn)
            first = last - len(new_objects) + 1
            probe = library.db.run("identifier.object_by_pub",
                                   (new_objects[0][0],), conn=conn)
            if probe is not None and probe["id"] == first:
                for k, (opub, _, _) in enumerate(new_objects):
                    oid_of[opub] = first + k
            else:  # fall back to the slow exact lookup
                for chunk in _in_chunks([p for p, _, _ in new_objects]):
                    ph = ",".join("?" for _ in chunk)
                    # binds the declared identifier.objects_by_pubs shape
                    for r in conn.execute(
                        f"SELECT id, pub_id FROM object "
                            f"WHERE pub_id IN ({ph})", chunk):
                        oid_of[r["pub_id"]] = r["id"]
        library.db.run_many(
            "identifier.link_paths",
            [(cas_id, oid_of[pub_of[i]], rows[i]["id"])
             for i, cas_id in ids.items()], conn=conn)
        tp = _mark("db_write", tp)

        # ---- op log: object creates, then ONE multi-field update per
        # file_path ({cas_id, object_id} in a single "u:cas_id+object_id"
        # op — the reference's three per-field passes, mod.rs:144/231/167,
        # are 3 op rows/file; this is ≤2 and 1 for linked files). Creates
        # go first so their HLC stamps precede the links and in-order
        # ingest resolves the object FK.
        n_ops += sync.bulk_shared_ops(conn, "object", create_specs)
        n_ops += sync.bulk_shared_ops(conn, "file_path", [
            (rows[i]["pub_id"], LINK_KIND, None, None,
             {"cas_id": cas_id, "object_id": pub_of[i]})
            for i, cas_id in ids.items()])
        tp = _mark("ops", tp)
    if own_tx:
        _mark("db_commit", tp)
    if cas_map is not None:
        # Job-lifetime map: with our own tx it updates only AFTER the
        # commit above (a rolled-back chunk must not leave uncommitted
        # rowids/pub_ids in the map for later chunks). Inside a caller-
        # owned multi-chunk tx it updates NOW — the next chunk in the
        # same transaction must dedup against these objects — and the
        # added keys ride in batch.cas_added so the caller pops them
        # back out if the whole transaction rolls back.
        for c, opub in by_cas.items():
            cas_map[c] = (oid_of[opub], opub)
        if not own_tx and batch is not None:
            batch.cas_added.extend(by_cas)
    if own_tx:
        # Standalone callers (watcher shallow-identify) count here; the
        # job path counts once per commit group in _step instead.
        if linked:
            IDENT_FILES.labels(outcome="linked").inc(linked)
        if created:
            IDENT_FILES.labels(outcome="created").inc(created)
        if read_errors:
            IDENT_FILES.labels(outcome="skipped").inc(len(read_errors))
    if n_ops:
        if own_tx:
            sync._notify_created()
        elif batch is not None:
            batch.n_ops += n_ops  # broadcast after the caller commits
    return linked, created, list(read_errors.values())


@register_job
class FileIdentifierJob(StatefulJob):
    NAME = "file_identifier"
    IS_BATCHED = True

    def __init__(self, *, location_id: int, sub_path: Optional[str] = None,
                 backend: str = "auto",
                 device_batch: Optional[int] = None):
        """`device_batch` decouples the device batch from the reference's
        100-file step (SURVEY.md §7 hard part 2): steps page the cursor
        in device_batch-file chunks (e.g. 4096-16384), each staged and
        hashed as ONE batched device call. Checkpointing stays exact —
        the cursor advances per step, and replayed chunks are idempotent
        (cas_id/object updates keyed by row id)."""
        if device_batch is not None and device_batch < 1:
            raise ValueError(f"device_batch must be >= 1, got {device_batch}")
        super().__init__(location_id=location_id, sub_path=sub_path,
                         backend=backend, device_batch=device_batch)
        self.location_id = location_id
        self.sub_path = sub_path
        self.backend = backend
        self.device_batch = device_batch

    @property
    def chunk_size(self) -> int:
        return self.device_batch or CHUNK_SIZE

    async def init(self, ctx: JobContext):
        db = ctx.db
        from ..locations.file_path_helper import load_location
        loc = load_location(db, self.location_id)
        sub_mat = sub_path_children_mat(self.location_id, self.sub_path)
        where, params = orphan_filters(self.location_id, 0, sub_mat)
        # binds the declared identifier.orphan_count shape
        count = db.query_one(
            f"SELECT COUNT(*) AS n FROM file_path WHERE {where}", params)["n"]
        if count == 0:
            raise EarlyFinish("no orphan file paths")
        chunk = self.chunk_size
        device_engaged = self.device_batch is not None or self.backend == "jax"
        if self.device_batch is None and self.backend in ("auto", "jax"):
            # Auto device engagement (VERDICT r1 item 3): big scans step
            # in device-batch chunks when the link probe says the device
            # pipeline beats the native plane (ops/staging.py policy).
            from ..ops.staging import auto_device_batch

            auto = auto_device_batch(count)
            if auto is not None:
                chunk = auto
                device_engaged = True
        if (self.device_batch is None and chunk == CHUNK_SIZE
                and self.backend in ("auto", "native")
                and count >= staging.AUTO_DEVICE_MIN_ORPHANS):
            # Big scan staying on the host plane: step in large chunks so
            # the per-chunk orchestration (page fetch, op build, commit)
            # amortizes — the wall is the host pipeline, not the hash.
            # Native-plane only: it streams per file in C++, while the
            # numpy fallback stages dense [B, 100 KiB] arrays per chunk
            # (~420 MiB at 4096) and must keep the small reference step.
            from .. import native as _native
            if _native.available():
                chunk = staging.AUTO_NATIVE_BATCH
        # Bulk-load trick for big scans: the cas_id/object_id indexes on
        # file_path exist for READ paths (dedup grouping, object →
        # paths lookups); during identify they only eat random B-tree
        # inserts — 2 index rows per file, measured ~15-20% of the 1M
        # wall in page churn. Drop them for the run and rebuild sorted
        # in finalize (~2-4 s/1M rows). Crash-safe: Database open
        # re-executes the eager CREATE INDEX IF NOT EXISTS DDL (SIGKILL
        # → next open rebuilds); cancel/failure restore via cleanup().
        # The cas_id index is only droppable when the preloaded cas map
        # will replace its probes — otherwise the per-chunk IN()
        # fallbacks would become full table scans.
        rebuild = count >= self.BULK_DROP_MIN_ORPHANS
        cas_preload = (await asyncio.to_thread(
            db.run, "store.object_count"))["n"] <= self.CAS_PRELOAD_MAX
        if rebuild:
            # One tiny DDL pair at job start; not worth a thread hop.
            # sdlint: ok[blocking-async]
            with db.write_tx() as conn:
                if cas_preload:
                    conn.execute(
                        "DROP INDEX IF EXISTS idx_file_path_cas_id")
                conn.execute(
                    "DROP INDEX IF EXISTS idx_file_path_object_id")
        # Commit batching: one transaction per STEP covering several
        # hash chunks — WAL commit overhead (page flushes shared across
        # chunks) amortizes, measured ~4.5 s of the 1M identify as
        # per-chunk commits. Capped so a commit group stays ≤ ~16k
        # files: the crash checkpoint's cursor only advances per step,
        # so a SIGKILL replays at most one commit group (idempotent,
        # keyed by row id), and pause latency stays bounded.
        # Two configurations keep one chunk per step: device-engaged
        # runs (their chunks are already 8-16k files, and a second
        # device dispatch must never run under the held write lock) and
        # hash-ahead hosts (≥2 usable cores — there the worker hash of
        # chunk k+1 overlapping chunk k's whole db+commit phase is
        # worth more than amortized commits, and batching would
        # serialize the worker behind the group). Commit batching
        # targets the remaining case: the single-core host plane, where
        # nothing overlaps anyway and per-chunk commits were pure
        # overhead.
        # Hash-ahead now covers device-engaged runs too, gated on the
        # depth-N pipeline being enabled (SDTPU_PIPELINE_DEPTH > 1)
        # AND buffer donation being on: donation is the actual safety
        # condition — the worker's stage+H2D+hash of chunk k+1 then no
        # longer pins a second batch's device inputs against chunk k's
        # in-flight dispatch (the old single-client-tunnel hazard that
        # forced device runs to serialize), so the device stream stays
        # fed through the whole db+commit phase. Depth 1 or
        # SDTPU_DONATE_BUFFERS=off restores the serial shape.
        from .. import flags as _flags
        from ..ops import overlap as _overlap
        pipe_depth = _overlap.pipeline_depth()
        hash_ahead = _usable_cpus() > 1 and (
            not device_engaged
            or (pipe_depth > 1
                and bool(_flags.get("SDTPU_DONATE_BUFFERS"))))
        commit_every = (1 if device_engaged or hash_ahead
                        else max(1, min(8, 16384 // chunk)))
        data = {
            "location_path": loc["path"],
            "sub_mat_path": sub_mat,
            "rebuild_indexes": rebuild,
            "cas_preload": cas_preload,
            # The resolved step size rides in `data` so pause/resume
            # replays use the same pagination the steps were counted for.
            "chunk_size": chunk,
            "commit_every": commit_every,
            # Recorded for the artifact trail (bench/perf_smoke report
            # it): which pipeline depth the device stream ran under.
            "pipeline_depth": pipe_depth if device_engaged else None,
            # Hash-ahead (stage+hash chunk i+1 in a worker thread while
            # chunk i's transaction commits) runs on the host planes
            # and, since the depth-N ring landed, on device-engaged
            # runs whenever SDTPU_PIPELINE_DEPTH > 1 AND
            # SDTPU_DONATE_BUFFERS is on (donated buffers are what
            # make a second in-flight device batch safe — see the
            # commit_every note above). It still needs a second USABLE
            # core (affinity/cgroup-aware, not cpu_count): measured on
            # a 1-core host it LOSES ~8% (WAL+synchronous=NORMAL
            # commits don't fsync, so there is no IO wait to hide
            # under — only GIL contention).
            "hash_ahead": hash_ahead,
            "cursor": 0,
            "linked": 0, "created": 0, "skipped": 0, "total_orphans": count,
        }
        steps = [{"chunk": i}
                 for i in range(-(-count // (chunk * commit_every)))]
        ctx.progress(task_count=len(steps),
                     message=f"identifying {count} orphan paths")
        return data, steps

    async def execute_step(self, ctx, data, step, step_number):
        return await asyncio.to_thread(self._step, ctx, data)

    def _fetch_page(self, ctx: JobContext, data: Dict[str, Any],
                    cursor: int) -> List[Dict[str, Any]]:
        where, params = orphan_filters(
            self.location_id, cursor, data["sub_mat_path"])
        # sqlite3.Row supports ["name"] access directly — no dict() copy.
        # binds the declared identifier.orphan_page shape
        return ctx.db.query(
            f"SELECT * FROM file_path WHERE {where} ORDER BY id ASC LIMIT ?",
            params + [data.get("chunk_size") or self.chunk_size])

    # Above this many existing objects the whole-library cas_id map is
    # not preloaded (memory: ~150 B/entry) and chunks fall back to the
    # per-chunk IN() probes.
    CAS_PRELOAD_MAX = 2_000_000
    # At or above this many orphans, the file_path cas_id/object_id
    # indexes are dropped for the run and rebuilt in finalize.
    BULK_DROP_MIN_ORPHANS = 100_000

    def _get_cas_map(self, ctx: JobContext, data: Dict[str, Any]):
        """Whole-library cas_id → (object id, pub_id) dict, built once
        per job run and maintained by identify_chunk — replaces ~250
        IN()-probe queries per 1M files. Rebuilt from the DB on resume,
        so replayed chunks link to pre-crash objects idempotently.

        The engage decision was made at init ("cas_preload" in data) —
        the same decision that gated dropping the cas_id probe index;
        deciding here again could diverge and leave probes unindexed.
        Pre-change resumed jobs (no key) decide now, their index is
        still in place."""
        m = getattr(self, "_cas_map", None)
        if m is not None:
            return None if m is False else m  # {} stays engaged
        enabled = data.get("cas_preload")
        if enabled is None:
            enabled = ctx.db.run("store.object_count")["n"] \
                <= self.CAS_PRELOAD_MAX
        if not enabled:
            self._cas_map = False
            return None
        m = {}
        for r in ctx.db.run("identifier.cas_map"):
            m.setdefault(r["c"], (r["oid"], r["opub"]))
        self._cas_map = m
        return m

    def _fetch_and_hash(self, ctx: JobContext, data: Dict[str, Any],
                        cursor: int):
        """Worker-thread body of the hash-ahead pipeline: page fetch,
        file staging, batched hashing — everything before the tx. Safe
        to run against the live DB: the page past the previous chunk's
        last row id is untouched by that chunk's updates. Returns
        (rows, prehashed, per-phase seconds) — the worker times its own
        phases so overlapped hashing is still attributed to `hash`, not
        smeared into the consumer's wait (the split perf_smoke
        reports)."""
        w: Dict[str, float] = {}
        t0 = time.perf_counter()
        rows = self._fetch_page(ctx, data, cursor)
        w["fetch"] = time.perf_counter() - t0
        if not rows:
            return rows, None, w
        t0 = time.perf_counter()
        files = stage_file_list(
            rows, self.location_id, data["location_path"])
        w["prep"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        # Round 10: the bucketed identify hash runs inside the
        # sanitizer's device scope — an undeclared retrace or host
        # transfer in this exact loop is what the jit registry's
        # contracts forbid (raise mode in tier-1, counters in prod).
        with jit_registry.device_scope("identify.hash"):
            ids, read_errors = cas_ids_for_files(
                files, backend=self.backend)
        w["hash"] = time.perf_counter() - t0
        return rows, (files, ids, read_errors), w

    def _timed_fetch(self, ctx: JobContext, data: Dict[str, Any],
                     cursor: int):
        """Fetch-only prefetch body (non-hash-ahead hosts)."""
        t0 = time.perf_counter()
        rows = self._fetch_page(ctx, data, cursor)
        return rows, None, {"fetch": time.perf_counter() - t0}

    def _take_page(self, ctx: JobContext, data: Dict[str, Any],
                   cursor: int, timings: Dict[str, float]):
        """One chunk's orphan page (+ prehashed payload when the
        prefetch was a hash-ahead one), honoring a matching prefetch.

        Phase accounting: worker-measured fetch/prep/hash seconds merge
        into `timings` at their TRUE cost; the time this thread spent
        blocked on the worker lands in `overlap_wait` (the un-hidden
        remainder of the overlap — with perfect overlap it tends to 0).
        Overlapped phases can therefore sum past step_total; the split
        is cost attribution, not a wall-clock partition."""
        tf = time.perf_counter()
        pre = getattr(self, "_prefetch", None)
        rows = prehashed = wtimings = None
        if pre is not None and pre[0] == cursor:
            try:
                rows, prehashed, wtimings = pre[1].result()
            except Exception:
                rows = prehashed = wtimings = None  # sync-path fallback
        self._prefetch = None
        if wtimings:
            for k, v in wtimings.items():
                timings[k] = timings.get(k, 0.0) + v
            timings["overlap_wait"] = (timings.get("overlap_wait", 0.0)
                                       + time.perf_counter() - tf)
        if rows is None:
            t0 = time.perf_counter()
            rows = self._fetch_page(ctx, data, cursor)
            timings["fetch"] = (timings.get("fetch", 0.0)
                                + time.perf_counter() - t0)
        return (rows if rows else None), prehashed

    def _stage_and_hash(self, rows, data: Dict[str, Any],
                        timings: Dict[str, float]):
        """Inline (main-thread) staging + batched hashing of one page —
        the path taken when the prefetch was fetch-only. Runs with the
        successor prefetch already in flight, so the next page's SELECT
        (or fetch+hash) hides under this work."""
        tp = time.perf_counter()
        files = stage_file_list(
            rows, self.location_id, data["location_path"])
        t1 = time.perf_counter()
        timings["prep"] = timings.get("prep", 0.0) + t1 - tp
        with jit_registry.device_scope("identify.hash"):
            ids, read_errors = cas_ids_for_files(
                files, backend=self.backend)
        timings["hash"] = (timings.get("hash", 0.0)
                           + time.perf_counter() - t1)
        return files, ids, read_errors

    def _step(self, ctx: JobContext, data: Dict[str, Any]) -> StepOutcome:
        tf = time.perf_counter()
        timings = data.setdefault("phase_s", {})
        # Registry mirror of the phase split: `timings` accumulates for
        # the job report; the per-step DELTA lands on the node-wide
        # phase counters so /metrics shows live attribution mid-run
        # (and perf_smoke --telemetry sources its split from here).
        phase_before = dict(timings)
        # _submit (not a raw _pool().submit): survives another Node's
        # concurrent shutdown_stage_pool() by landing on a fresh pool.
        from ..ops.staging import _submit

        # Phase 1 — collect the whole commit group OUTSIDE any
        # transaction: fetch + stage + hash never run (or wait) under
        # the held write lock. Per chunk: take the page, submit the
        # successor's prefetch, THEN hash inline — so the next page's
        # SELECT (or worker fetch+hash) hides under this chunk's
        # hashing, and the last submitted prefetch (the next step's
        # first chunk) hides under phase 2's db work. Hash-ahead hosts
        # run commit_every=1 (set at init), so their worker hash of
        # chunk k+1 overlaps chunk k's whole phase 2 — the per-chunk
        # overlap the round-5 pipeline had.
        commit_every = data.get("commit_every") or 1
        cursor = data["cursor"]
        chunks: List[tuple] = []
        for _ in range(commit_every):
            rows, prehashed = self._take_page(ctx, data, cursor, timings)
            if rows is None:
                break
            cursor = rows[-1]["id"] + 1
            if data.get("hash_ahead"):
                if prehashed is None:
                    # Cold start (no matching hash-ahead prefetch —
                    # job start or post-resume): hash THIS chunk
                    # before submitting the next chunk's worker, so a
                    # device backend's first-call jit compile happens
                    # once, serially. Two threads tracing the same
                    # cold program concurrently buy no overlap (the
                    # second blocks on the compile anyway) and
                    # stretch every event-loop callback under the
                    # GIL — observed as sanitizer loop stalls on
                    # 2-core hosts. Warm chunks keep the submit-first
                    # order, so steady-state overlap is unchanged.
                    prehashed = self._stage_and_hash(rows, data,
                                                     timings)
                self._prefetch = (cursor, _submit(
                    self._fetch_and_hash, ctx, data, cursor))
            else:
                # Fetch-only prefetch (host planes): submit BEFORE the
                # inline hash every chunk — the next page fetch hides
                # under this chunk's hashing.
                self._prefetch = (cursor, _submit(
                    self._timed_fetch, ctx, data, cursor))
                if prehashed is None:
                    prehashed = self._stage_and_hash(rows, data,
                                                     timings)
            chunks.append((rows, prehashed))
        if not chunks:
            return StepOutcome()

        # Phase 2 — ONE transaction for the whole commit group
        # (commit_every chunks): WAL pages dirtied by several chunks
        # flush once at the group commit instead of per chunk, and the
        # write lock covers DB WORK ONLY (sub-second for a ~16k-file
        # group). The cursor in `data` — what the 3 s crash checkpoint
        # serializes — only advances after the commit, so a SIGKILL
        # replays at most one commit group, idempotently
        # (cas_id/object updates keyed by row id).
        cas_map = self._get_cas_map(ctx, data)
        batch = TxBatch()
        linked = created = 0
        errors: List[str] = []
        db = ctx.db
        try:
            with db.write_tx() as conn:
                for rows, prehashed in chunks:
                    lk, cr, errs = identify_chunk(
                        ctx.library, self.location_id,
                        data["location_path"], rows, self.backend,
                        timings=timings, prehashed=prehashed,
                        cas_map=cas_map, conn=conn, batch=batch)
                    linked += lk
                    created += cr
                    errors.extend(errs)
                t_commit = time.perf_counter()
        except BaseException:
            # The rolled-back transaction's objects never existed: pop
            # their cas-map entries or later chunks would link
            # file_paths to phantom row ids.
            if cas_map is not None:
                for c in batch.cas_added:
                    cas_map.pop(c, None)
            raise
        timings["db_commit"] = (timings.get("db_commit", 0.0)
                                + time.perf_counter() - t_commit)
        if batch.n_ops:
            ctx.library.sync._notify_created()
        data["cursor"] = cursor
        timings["step_total"] = (timings.get("step_total", 0.0)
                                 + time.perf_counter() - tf)
        for phase, total in timings.items():
            delta = total - phase_before.get(phase, 0.0)
            if delta > 0:
                IDENT_PHASE_SECONDS.labels(phase=phase).inc(delta)
        if linked:
            IDENT_FILES.labels(outcome="linked").inc(linked)
        if created:
            IDENT_FILES.labels(outcome="created").inc(created)
        if errors:
            IDENT_FILES.labels(outcome="skipped").inc(len(errors))
        data["linked"] += linked
        data["created"] += created
        data["skipped"] += len(errors)
        ctx.progress(message=(
            f"identified {data['linked'] + data['created']} of "
            f"{data['total_orphans']} paths"))
        return StepOutcome(
            errors=errors,
            metadata={
                "total_objects_linked": data["linked"],
                "total_objects_created": data["created"],
                "total_skipped": data["skipped"],
                "cursor": data["cursor"],
            },
        )

    @staticmethod
    def _restore_indexes(db) -> None:
        """Recreate the bulk-dropped read indexes. Idempotent (IF NOT
        EXISTS): a no-op when they were never dropped."""
        with db.write_tx() as conn:
            conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_file_path_cas_id "
                "ON file_path (cas_id)")
            conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_file_path_object_id "
                "ON file_path (object_id)")

    async def cleanup(self, ctx, data):
        """Cancel/failure path: finalize never runs, so restore the
        indexes here (data may be None — restore unconditionally, it
        is free when they exist)."""
        await asyncio.to_thread(self._restore_indexes, ctx.db)

    async def finalize(self, ctx, data, metadata):
        if data.get("rebuild_indexes"):
            t0 = time.perf_counter()
            self._restore_indexes(ctx.db)
            data.setdefault("phase_s", {})["index_rebuild"] = (
                time.perf_counter() - t0)
        # Publish the per-phase wall-time breakdown (fetch/prep/hash/db/
        # ops seconds across all chunks) so workload runs can see where
        # the ms/file goes — the profile VERDICT r2 asked for.
        phase = data.get("phase_s")
        if phase:
            metadata["phase_ms"] = {
                k: round(v * 1000.0, 1) for k, v in sorted(phase.items())}
            metadata["chunk_size"] = data.get("chunk_size")
            if data.get("pipeline_depth") is not None:
                metadata["pipeline_depth"] = data["pipeline_depth"]
        return metadata
