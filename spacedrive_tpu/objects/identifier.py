"""FileIdentifierJob: CAS-ID every orphan file_path, link/create objects.

The flagship hot path (SURVEY.md §3.3). Behavior mirrors the reference job
(/root/reference/core/src/object/file_identifier/file_identifier_job.rs:72-309
and mod.rs:100-331): cursor-paginated chunks of CHUNK_SIZE orphans
(object_id IS NULL, is_dir = 0), per chunk: compute kind + CAS ID, write
cas_ids via sync, link file_paths to existing objects matching by cas_id,
create objects for the rest.

TPU-first deviations:
- the per-chunk hashing is a *batched* staged pipeline
  (ops/staging.cas_ids_for_files) on the configured backend
  ("oracle" | "numpy" | "jax" | "auto") instead of per-file streaming;
- files in one chunk sharing a cas_id share ONE new object (the reference
  creates an object per file_path and only dedups against earlier chunks).
"""

from __future__ import annotations

import asyncio
import time
import uuid as uuidlib
from typing import Any, Dict, List, Optional, Tuple

from ..files import resolve_kind
from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.file_path_helper import materialized_like, sub_path_children_mat
from ..locations.paths import IsolatedPath
from ..ops.staging import cas_ids_for_files

CHUNK_SIZE = 100  # file_identifier/mod.rs:36


def orphan_filters(location_id: int, cursor: int,
                   sub_mat_path: Optional[str]) -> Tuple[str, list]:
    """WHERE clause for orphan file_paths
    (orphan_path_filters, file_identifier_job.rs:245-270)."""
    where = ("object_id IS NULL AND is_dir = 0 AND location_id = ? "
             "AND id >= ?")
    params: list = [location_id, cursor]
    where = materialized_like(where, params, sub_mat_path)
    return where, params


def identify_chunk(library, location_id: int, location_path: str,
                   rows: List[Dict[str, Any]], backend: str = "auto",
                   ) -> Tuple[int, int, List[str]]:
    """The identifier's per-chunk kernel (identifier_job_step,
    mod.rs:100-331): batched CAS hashing, cas_id writes, object
    linking/creation — all through sync. Returns (linked, created,
    errors). Shared by the job and the shallow/watcher path."""
    db, sync = library.db, library.sync
    files: List[Tuple[str, int]] = []
    for r in rows:
        iso = IsolatedPath.from_db_row(
            location_id, False, r["materialized_path"],
            r["name"] or "", r["extension"] or "")
        size = int.from_bytes(r["size_in_bytes_bytes"] or b"", "big")
        files.append((iso.join_on(location_path), size))

    # ---- batched hashing (the TPU-fed kernel) ----
    ids, read_errors = cas_ids_for_files(files, backend=backend)
    kinds = {
        i: int(resolve_kind(files[i][0], ext=rows[i]["extension"] or ""))
        for i in ids
    }

    # ---- 1. write cas_ids through sync (mod.rs:144-165) ----
    ops = []
    with db.tx() as conn:
        for i, cas_id in ids.items():
            conn.execute(
                "UPDATE file_path SET cas_id = ? WHERE id = ?",
                (cas_id, rows[i]["id"]))
            ops.append(sync.shared_update(
                "file_path", rows[i]["pub_id"], "cas_id", cas_id))
        sync._insert_op_rows(conn, ops)

    # ---- 2. link to existing objects by cas_id (mod.rs:167-225) ----
    cas_list = sorted({c for c in ids.values() if c})
    existing: Dict[str, Tuple[int, bytes]] = {}
    if cas_list:
        ph = ",".join("?" for _ in cas_list)
        for r in db.query(
            f"SELECT fp.cas_id AS cas_id, o.id AS oid, o.pub_id AS opub "
            f"FROM file_path fp JOIN object o ON o.id = fp.object_id "
            f"WHERE fp.cas_id IN ({ph})", cas_list):
            existing.setdefault(r["cas_id"], (r["oid"], r["opub"]))
    linked = 0
    ops = []
    with db.tx() as conn:
        for i, cas_id in ids.items():
            if cas_id is None or cas_id not in existing:
                continue
            oid, opub = existing[cas_id]
            conn.execute(
                "UPDATE file_path SET object_id = ? WHERE id = ?",
                (oid, rows[i]["id"]))
            ops.append(sync.shared_update(
                "file_path", rows[i]["pub_id"], "object_id", opub))
            linked += 1
        sync._insert_op_rows(conn, ops)

    # ---- 3. create objects for the rest (mod.rs:231-331) ----
    need_new = [i for i, c in ids.items() if c is None or c not in existing]
    created = 0
    ops = []
    with db.tx() as conn:
        by_cas: Dict[str, Tuple[int, bytes]] = {}
        for i in need_new:
            cas_id = ids[i]
            if cas_id is not None and cas_id in by_cas:
                oid, opub = by_cas[cas_id]  # same-chunk duplicate
            else:
                opub = uuidlib.uuid4().bytes
                date_created = rows[i]["date_created"]
                oid = conn.execute(
                    "INSERT INTO object (pub_id, kind, date_created) "
                    "VALUES (?, ?, ?)",
                    (opub, kinds[i], date_created)).lastrowid
                ops.extend(sync.shared_create(
                    "object", opub,
                    {"kind": kinds[i], "date_created": date_created}))
                created += 1
                if cas_id is not None:
                    by_cas[cas_id] = (oid, opub)
            conn.execute(
                "UPDATE file_path SET object_id = ? WHERE id = ?",
                (oid, rows[i]["id"]))
            ops.append(sync.shared_update(
                "file_path", rows[i]["pub_id"], "object_id", opub))
        sync._insert_op_rows(conn, ops)
    if ops:
        sync._notify_created()
    return linked, created, list(read_errors.values())


@register_job
class FileIdentifierJob(StatefulJob):
    NAME = "file_identifier"
    IS_BATCHED = True

    def __init__(self, *, location_id: int, sub_path: Optional[str] = None,
                 backend: str = "auto",
                 device_batch: Optional[int] = None):
        """`device_batch` decouples the device batch from the reference's
        100-file step (SURVEY.md §7 hard part 2): steps page the cursor
        in device_batch-file chunks (e.g. 4096-16384), each staged and
        hashed as ONE batched device call. Checkpointing stays exact —
        the cursor advances per step, and replayed chunks are idempotent
        (cas_id/object updates keyed by row id)."""
        if device_batch is not None and device_batch < 1:
            raise ValueError(f"device_batch must be >= 1, got {device_batch}")
        super().__init__(location_id=location_id, sub_path=sub_path,
                         backend=backend, device_batch=device_batch)
        self.location_id = location_id
        self.sub_path = sub_path
        self.backend = backend
        self.device_batch = device_batch

    @property
    def chunk_size(self) -> int:
        return self.device_batch or CHUNK_SIZE

    async def init(self, ctx: JobContext):
        db = ctx.db
        from ..locations.file_path_helper import load_location
        loc = load_location(db, self.location_id)
        sub_mat = sub_path_children_mat(self.location_id, self.sub_path)
        where, params = orphan_filters(self.location_id, 0, sub_mat)
        count = db.query_one(
            f"SELECT COUNT(*) AS n FROM file_path WHERE {where}", params)["n"]
        if count == 0:
            raise EarlyFinish("no orphan file paths")
        chunk = self.chunk_size
        if self.device_batch is None and self.backend in ("auto", "jax"):
            # Auto device engagement (VERDICT r1 item 3): big scans step
            # in device-batch chunks when the link probe says the device
            # pipeline beats the native plane (ops/staging.py policy).
            from ..ops.staging import auto_device_batch

            auto = auto_device_batch(count)
            if auto is not None:
                chunk = auto
        data = {
            "location_path": loc["path"],
            "sub_mat_path": sub_mat,
            # The resolved step size rides in `data` so pause/resume
            # replays use the same pagination the steps were counted for.
            "chunk_size": chunk,
            "cursor": 0,
            "linked": 0, "created": 0, "skipped": 0, "total_orphans": count,
        }
        steps = [{"chunk": i} for i in range(-(-count // chunk))]
        ctx.progress(task_count=len(steps),
                     message=f"identifying {count} orphan paths")
        return data, steps

    async def execute_step(self, ctx, data, step, step_number):
        return await asyncio.to_thread(self._step, ctx, data)

    def _step(self, ctx: JobContext, data: Dict[str, Any]) -> StepOutcome:
        where, params = orphan_filters(
            self.location_id, data["cursor"], data["sub_mat_path"])
        rows = [dict(r) for r in ctx.db.query(
            f"SELECT * FROM file_path WHERE {where} ORDER BY id ASC LIMIT ?",
            params + [data.get("chunk_size") or self.chunk_size])]
        if not rows:
            return StepOutcome()
        linked, created, errors = identify_chunk(
            ctx.library, self.location_id, data["location_path"], rows,
            self.backend)
        data["cursor"] = rows[-1]["id"] + 1
        data["linked"] += linked
        data["created"] += created
        data["skipped"] += len(errors)
        ctx.progress(message=(
            f"identified {data['linked'] + data['created']} of "
            f"{data['total_orphans']} paths"))
        return StepOutcome(
            errors=errors,
            metadata={
                "total_objects_linked": data["linked"],
                "total_objects_created": data["created"],
                "total_skipped": data["skipped"],
                "cursor": data["cursor"],
            },
        )

    async def finalize(self, ctx, data, metadata):
        return metadata
