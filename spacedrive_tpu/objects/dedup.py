"""Duplicate analytics: exact CAS-ID groups + perceptual near-dup job.

Exact duplicates mirror the identifier's linking invariant (same cas_id →
same object, /root/reference/core/src/object/file_identifier/mod.rs:167-225):
`exact_duplicate_groups` reports objects with multiple file_paths, the
dedup view a file manager shows for "reclaimable space".

Near-dup search is net-new (BASELINE.json config 4): a StatefulJob that
pHashes every image (ops/phash: DCT matmuls on device), persists hashes
on media_data rows, then runs the tiled Hamming all-pairs
(ops/hamming.near_dup_pairs; LSH banding beyond ~100k) and stores pairs
in near_dup_pair.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

from ..jobs.job import EarlyFinish, JobContext, StatefulJob, StepOutcome, register_job
from ..locations.file_path_helper import job_prologue
from ..locations.paths import IsolatedPath
from ..media.exif import MEDIA_DATA_EXTENSIONS
from ..ops.phash import phash_files, phash_from_bytes, phash_to_bytes

PHASH_BATCH = 256
DEFAULT_THRESHOLD = 10
# Beyond this many hashes, exact all-pairs gives way to LSH bucketing
# (SURVEY.md §7 hard-part 4).
ALL_PAIRS_LIMIT = 100_000

PHASHABLE_EXTENSIONS = sorted(
    MEDIA_DATA_EXTENSIONS | {"bmp", "gif", "ico", "tif"})


def exact_duplicate_groups(library, location_id: Optional[int] = None,
                           limit: int = 1000) -> List[Dict[str, Any]]:
    """Objects whose cas_id is shared by multiple file_paths —
    [{cas_id, object_pub_id, count, total_bytes, paths:[...]}]."""
    where = "fp.cas_id IS NOT NULL"
    params: List[Any] = []
    if location_id is not None:
        where += " AND fp.location_id = ?"
        params.append(location_id)
    # binds the declared dedup.exact_groups shape
    rows = library.db.query(
        f"SELECT fp.cas_id AS cas_id, COUNT(*) AS n, "
        f"o.pub_id AS object_pub_id "
        f"FROM file_path fp JOIN object o ON o.id = fp.object_id "
        f"WHERE {where} GROUP BY fp.cas_id HAVING n > 1 "
        f"ORDER BY n DESC LIMIT ?", params + [limit])
    out = []
    for r in rows:
        paths = library.db.run("dedup.paths_by_cas", (r["cas_id"],))
        sizes = [int.from_bytes(p["size_in_bytes_bytes"] or b"", "big")
                 for p in paths]
        pub = r["object_pub_id"]
        out.append({
            "cas_id": r["cas_id"],
            # hex, not raw bytes: this dict crosses the JSON-RPC surface
            "object_pub_id": pub.hex() if isinstance(pub, bytes) else pub,
            "count": r["n"],
            "total_bytes": sum(sizes),
            "reclaimable_bytes": sum(sizes) - (sizes[0] if sizes else 0),
            "paths": [
                f"{p['materialized_path']}{p['name']}"
                + (f".{p['extension']}" if p["extension"] else "")
                for p in paths
            ],
        })
    return out


@register_job
class NearDupDetectorJob(StatefulJob):
    """Two phases: (1) pHash every un-hashed image in PHASH_BATCH chunks,
    (2) one compare step running the device all-pairs and persisting
    near_dup_pair rows."""

    NAME = "near_dup_detector"
    IS_BATCHED = True

    def __init__(self, *, location_id: int,
                 threshold: int = DEFAULT_THRESHOLD,
                 sub_path: Optional[str] = None, backend: str = "auto"):
        super().__init__(location_id=location_id, threshold=threshold,
                         sub_path=sub_path, backend=backend)
        self.location_id = location_id
        self.threshold = threshold
        self.sub_path = sub_path
        self.backend = backend

    def _init_sync(self, ctx: JobContext):
        db = ctx.db
        ph = ",".join("?" for _ in PHASHABLE_EXTENSIONS)
        loc, where, params = job_prologue(
            db, self.location_id, self.sub_path,
            f"fp.location_id = ? AND fp.is_dir = 0 AND "
            f"fp.object_id IS NOT NULL AND LOWER(fp.extension) IN ({ph})",
            [self.location_id, *PHASHABLE_EXTENSIONS])
        where = where.replace("materialized_path LIKE",
                              "fp.materialized_path LIKE")
        # binds the declared dedup.image_rows shape
        rows = db.query(
            f"SELECT fp.id, fp.object_id, fp.materialized_path, fp.name, "
            f"fp.extension, md.phash AS phash "
            f"FROM file_path fp "
            f"LEFT JOIN media_data md ON md.object_id = fp.object_id "
            f"WHERE {where} ORDER BY fp.id", params)
        if not rows:
            raise EarlyFinish("no images to hash")
        to_hash = [
            {"id": r["id"], "object_id": r["object_id"],
             "materialized_path": r["materialized_path"],
             "name": r["name"] or "", "extension": r["extension"] or ""}
            for r in rows if r["phash"] is None
        ]
        steps: List[Any] = []
        for i in range(0, len(to_hash), PHASH_BATCH):
            steps.append({"kind": "hash",
                          "rows": to_hash[i:i + PHASH_BATCH]})
        steps.append({"kind": "compare"})
        data = {"location_path": loc["path"], "hashed": 0,
                "pairs_found": 0, "total_images": len(rows)}
        ctx.progress(task_count=len(steps))
        return data, steps

    async def execute_step(self, ctx, data, step, step_number):
        if step["kind"] == "hash":
            return await asyncio.to_thread(self._hash_step, ctx, data, step)
        return await asyncio.to_thread(self._compare_step, ctx, data)

    def _hash_step(self, ctx: JobContext, data, step) -> StepOutcome:
        db = ctx.db
        rows = step["rows"]
        paths = []
        for r in rows:
            iso = IsolatedPath.from_db_row(
                self.location_id, False, r["materialized_path"],
                r["name"], r["extension"])
            paths.append(iso.join_on(data["location_path"]))
        hashes, errors = phash_files(paths, backend=self.backend)
        with db.write_tx() as conn:
            for i, words in hashes.items():
                blob = phash_to_bytes(words)
                # UPDATE-then-INSERT fallback decides per ROW on
                # rowcount — not batchable; one tx for the chunk
                cur = ctx.db.run(  # sdlint: ok[tx-shape]
                    "dedup.set_phash",
                    (blob, rows[i]["object_id"]), conn=conn)
                if cur.rowcount == 0:
                    ctx.db.run(  # sdlint: ok[tx-shape]
                        "dedup.insert_phash_row",
                        (rows[i]["object_id"], blob), conn=conn)
        data["hashed"] += len(hashes)
        ctx.progress(message=f"hashed {data['hashed']} images")
        return StepOutcome(errors=errors,
                           metadata={"hashed": data["hashed"]})

    def _compare_step(self, ctx: JobContext, data) -> StepOutcome:
        import numpy as np
        from ..ops.hamming import near_dup_pairs, near_dup_pairs_lsh
        db = ctx.db
        rows = db.run("dedup.phashes_for_location",
                      (self.location_id,))
        if len(rows) < 2:
            return StepOutcome(metadata={"pairs": 0})
        object_ids = [r["object_id"] for r in rows]
        digests = np.stack([phash_from_bytes(r["phash"]) for r in rows])

        from ..ops.blake3_pallas import supported as tpu_present
        errors = []
        if len(rows) <= ALL_PAIRS_LIMIT or tpu_present():
            # Exact — the two-pass device sweep holds to 1M+ digests
            # (tools/near_dup_scale.py records runtime + recall=1) — up
            # to the MAX_TOTAL_PAIRS output budget; truncation in
            # degenerate clusters is surfaced as a job error.
            stats: dict = {}
            pairs = near_dup_pairs(digests, self.threshold, stats=stats)
            if stats.get("truncated_pairs"):
                errors.append(
                    f"near-dup pair list truncated: ~"
                    f"{stats['truncated_pairs']} pairs in degenerate "
                    "near-identical clusters were dropped "
                    "(MAX_TOTAL_PAIRS budget)")
        else:
            # No device at huge N: probabilistic LSH fallback (recall
            # measured ~0.43 vs exact at threshold 10, near_dup_pairs_lsh).
            pairs = near_dup_pairs_lsh(digests, self.threshold)

        now = int(time.time())
        pair_rows = []
        for i, j in pairs:
            a, b = sorted((object_ids[i], object_ids[j]))
            if a == b:
                continue  # two file_paths of one object: exact dup
            d = int(np.sum(np.unpackbits(
                (digests[i] ^ digests[j]).astype(">u4").view(np.uint8))))
            pair_rows.append((a, b, d, now))
        with db.write_tx() as conn:
            db.run_many("dedup.upsert_pair", pair_rows, conn=conn)
        data["pairs_found"] = len(pairs)
        return StepOutcome(errors=errors, metadata={"pairs": len(pairs)})

    async def finalize(self, ctx, data, metadata):
        metadata.setdefault("hashed", data["hashed"])
        metadata["pairs"] = data["pairs_found"]
        metadata["total_images"] = data["total_images"]
        return metadata


def near_duplicates(library, location_id: Optional[int] = None,
                    max_distance: int = DEFAULT_THRESHOLD,
                    limit: int = 1000) -> List[Dict[str, Any]]:
    """Query stored near-dup pairs with object/file detail."""
    rows = library.db.run("dedup.pairs_within", (max_distance, limit))
    out = []
    for r in rows:
        def paths_of(oid):
            return [
                f"{p['materialized_path']}{p['name']}"
                + (f".{p['extension']}" if p["extension"] else "")
                for p in library.db.run("dedup.paths_for_object",
                                        (oid,))
            ]
        out.append({
            "distance": r["distance"],
            "object_a": r["object_a_id"], "object_b": r["object_b_id"],
            "paths_a": paths_of(r["object_a_id"]),
            "paths_b": paths_of(r["object_b_id"]),
        })
    return out
