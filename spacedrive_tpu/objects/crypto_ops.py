"""File encrypt/decrypt jobs.

The reference scaffolds these jobs but ships them commented out
(/root/reference/core/src/object/fs/{encrypt,decrypt}.rs — init types
FileEncryptorJobInit{location_id, path_id, key_uuid, algorithm,
metadata, preview_media} / FileDecryptorJobInit{…, output_path}); this
framework implements them as working StatefulJobs over the crypto
subsystem: header + keyslot + STREAM content in 1 MiB blocks, optional
sealed metadata (original name/kind) and preview-media (thumbnail)
attachments, optional secure-erase of the plaintext after sealing.
"""

from __future__ import annotations

import asyncio
import os
from typing import List, Optional

from .. import persist
from ..crypto.header import decrypt_file, encrypt_file
from ..crypto.hashing import HashingAlgorithm, Params
from ..crypto.primitives import Protected
from ..crypto.stream import Algorithm
from ..jobs.job import EarlyFinish, StepOutcome, register_job
from .fs_ops import _FsJobBase, _file_datas, find_available_filename_for_duplicate

ENCRYPTED_EXT = "sdtpu"


def _looks_like_completed_seal(src: str, target: str,
                               password: str | None = None) -> bool:
    """Replay detection: `target` is a fully-written seal of a file at
    least as large as `src` (header parses; sealed stream ≥ source;
    target postdates the source's last write). When `password` is given
    it must also unlock the header — the cost of one KDF round-trip is
    nothing next to what the erase_original path would otherwise risk:
    treating an OLD seal under a DIFFERENT password as this job's output
    and erasing the only plaintext."""
    from ..crypto.header import FileHeader
    from ..crypto.primitives import Protected

    try:
        with open(target, "rb") as f:
            header = FileHeader.deserialize(f)
            header_end = f.tell()
        if (os.path.getsize(target) - header_end < os.path.getsize(src)
                or os.path.getmtime(target) < os.path.getmtime(src)):
            return False
        if password is not None:
            header.decrypt_master_key(Protected(password.encode()))
        return True
    except (OSError, ValueError):
        return False


@register_job
class FileEncryptorJob(_FsJobBase):
    NAME = "file_encryptor"  # fs/encrypt.rs FileEncryptorJobInit
    # The password must never be written to the job table (the reference
    # routes key material through key-manager UUIDs for the same
    # reason); a cold-resumed job gets password=None and its remaining
    # steps error out non-fatally.
    TRANSIENT_ARGS = frozenset({"password"})

    def __init__(self, *, location_id: int, file_path_ids: List[int],
                 password: str | None,
                 algorithm: str = Algorithm.XCHACHA20_POLY1305.value,
                 hashing_algorithm: str = HashingAlgorithm.ARGON2ID.value,
                 params: str = Params.STANDARD.value,
                 with_metadata: bool = True,
                 erase_original: bool = False):
        super().__init__(
            location_id=location_id, file_path_ids=file_path_ids,
            password=password, algorithm=algorithm,
            hashing_algorithm=hashing_algorithm, params=params,
            with_metadata=with_metadata, erase_original=erase_original)
        self.password = password
        self.algorithm = Algorithm(algorithm)
        self.hashing_algorithm = HashingAlgorithm(hashing_algorithm)
        self.params = Params(params)
        self.with_metadata = with_metadata
        self.erase_original = erase_original

    async def init(self, ctx: JobContext):
        path = await asyncio.to_thread(self._location_path, ctx)
        fds = await asyncio.to_thread(
            _file_datas, ctx.db, self.location_id, path,
            self.file_path_ids)
        steps = [fd for fd in fds if not fd["is_dir"]]
        if not steps:
            raise EarlyFinish("nothing to encrypt")
        return {"location_path": path}, steps

    async def execute_step(self, ctx, data, step, step_number):
        if self.password is None:
            return StepOutcome(errors=[
                "password not available after cold resume; re-run the "
                "encrypt job"])

        # The "read" here is the PLAINTEXT SOURCE, not the sealed
        # artifact; target collisions get a fresh name
        # (find_available_filename_for_duplicate) and the jobs system
        # serializes a job's steps.
        # sdlint: ok[crash-atomicity]
        def run() -> StepOutcome:
            src = step["full_path"]
            if not os.path.exists(src):
                return StepOutcome(errors=[f"source missing: {src}"])
            target = src + "." + ENCRYPTED_EXT
            if os.path.exists(target):
                if _looks_like_completed_seal(src, target, self.password):
                    # Replayed step (idempotency contract, jobs/job.py):
                    # this step already finished before the interruption —
                    # but a crash between seal and erase must not leave
                    # the plaintext behind.
                    if self.erase_original:
                        from ..crypto.erase import secure_erase

                        secure_erase(src, passes=1, unlink=True)
                    return StepOutcome()
                target = find_available_filename_for_duplicate(target)
            metadata = None
            if self.with_metadata:
                metadata = {"name": os.path.basename(src),
                            "size": os.path.getsize(src)}
            # Seal into a temp name and rename on success so an
            # interrupted run never leaves a truncated file that passes
            # for a valid .sdtpu.
            part = target + ".part"
            try:
                # Streamed body (multi-GB sources can't buffer), so a
                # bare write into the .part is the only option; the
                # declared seal below makes the commit durable+atomic.
                with open(src, "rb") as fin, \
                        open(part, "wb") as fout:  # sdlint: ok[io-durability]
                    encrypt_file(
                        fin, fout, Protected(self.password.encode()),
                        algorithm=self.algorithm,
                        hashing_algorithm=self.hashing_algorithm,
                        params=self.params, metadata=metadata)
                persist.seal("object.sealed", part, target)
            except Exception as e:
                try:
                    os.remove(part)
                except OSError:
                    pass
                return StepOutcome(errors=[f"{src}: {e}"])
            if self.erase_original:
                from ..crypto.erase import secure_erase

                secure_erase(src, passes=1, unlink=True)
            return StepOutcome(metadata={"encrypted": target})
        return await asyncio.to_thread(run)


@register_job
class FileDecryptorJob(_FsJobBase):
    NAME = "file_decryptor"  # fs/decrypt.rs FileDecryptorJobInit
    TRANSIENT_ARGS = frozenset({"password"})

    def __init__(self, *, location_id: int, file_path_ids: List[int],
                 password: str | None, output_path: Optional[str] = None):
        super().__init__(location_id=location_id,
                         file_path_ids=file_path_ids, password=password,
                         output_path=output_path)
        self.password = password
        self.output_path = output_path

    async def init(self, ctx: JobContext):
        path = await asyncio.to_thread(self._location_path, ctx)
        fds = await asyncio.to_thread(
            _file_datas, ctx.db, self.location_id, path,
            self.file_path_ids)
        steps = [fd for fd in fds if not fd["is_dir"]]
        if not steps:
            raise EarlyFinish("nothing to decrypt")
        return {"location_path": path}, steps

    async def execute_step(self, ctx, data, step, step_number):
        if self.password is None:
            return StepOutcome(errors=[
                "password not available after cold resume; re-run the "
                "decrypt job"])

        def run() -> StepOutcome:
            src = step["full_path"]
            if not os.path.exists(src):
                return StepOutcome(errors=[f"source missing: {src}"])
            if self.output_path and len(self.file_path_ids) == 1:
                target = self.output_path
            elif src.endswith("." + ENCRYPTED_EXT):
                target = src[: -(len(ENCRYPTED_EXT) + 1)]
            else:
                target = src + ".decrypted"
            if os.path.exists(target):
                target = find_available_filename_for_duplicate(target)
            try:
                # Streamed decrypt into the caller-owned target
                # (multi-GB bodies can't buffer); a failed run removes
                # the partial below.
                # sdlint: ok[io-durability]
                with open(src, "rb") as fin, open(target, "wb") as fout:
                    decrypt_file(fin, fout,
                                 Protected(self.password.encode()))
            except Exception as e:
                try:
                    os.remove(target)
                except OSError:
                    pass
                return StepOutcome(errors=[f"{src}: {e}"])
            return StepOutcome(metadata={"decrypted": target})
        return await asyncio.to_thread(run)
