"""Opt-in runtime sanitizer: the dynamic half of tools/sdlint.

Static analysis (tools/sdlint) proves what it can from the AST; this
module checks the same invariant families at runtime, in the spirit of
ThreadSanitizer's dynamic-annotation checking (Serebryany &
Iskhodzhanov, WBIA 2009) scaled down to the three discipline rules this
engine actually depends on:

- **Event-loop stall detector** — every asyncio callback/task step is
  timed (one `Handle._run` wrap, two clock reads); a step hogging the
  loop past `SDTPU_SANITIZE_STALL_S` seconds is a violation. This is
  the runtime twin of sdlint's blocking-in-async pass: whatever the
  interprocedural walk missed shows up here as a measured stall.
- **Lock-order recorder + cycle check** — `tracked_lock()` /
  `tracked_rlock()` wrap the store's locks; each first acquisition
  while other tracked locks are held records held→new edges in a
  process-global lock graph, and an acquisition that would close a
  cycle (the classic AB/BA deadlock — the PR 1 `store/db.py`
  reader-registration shape) is flagged BEFORE blocking on the lock,
  so `raise` mode surfaces the deadlock instead of hanging CI.
- **Write-lock-held-across-await assertion** — when an event-loop
  callback returns control to the loop with a tracked lock still held
  by the loop thread, a coroutine suspended mid-critical-section (the
  `with db.tx(): ... await ...` anti-pattern): every other task on the
  loop can now deadlock behind a lock whose owner only resumes via the
  same loop.
- **Device-contract guards** (round 10, armed via
  `ops/jit_registry.arm()` at install) — the runtime twins of sdlint's
  jit-stability and host-transfer passes: registered jit entry points
  count retraces against their declared budgets
  (`sd_jit_retraces_total{fn}` / `sd_jit_cache_size{fn}`, violation
  kind `jit_retrace_budget`), and `device_scope()` regions arm JAX's
  device-to-host transfer guard so an undeclared result fetch raises
  in tier-1 and logs in production (kind `host_transfer`; declared
  fetches go through `io(name)` scopes).
- **Task-supervisor detections** (round 11, reported through
  `record()` by `tasks.py` — the runtime twin of sdlint's
  task-lifecycle/cancellation-safety passes): a supervised task dying
  with an unretrieved exception is a `task_exception`, and a task
  surviving `Node.shutdown`'s reap grace is a `task_orphaned`
  (raised at the reap in tier-1).
- **Channel overflow detection** (round 12, armed via `channels.arm()`
  at install — the runtime twin of sdlint's queue-discipline and
  backpressure passes): a send_nowait burst past a declared frame
  window, or a nowait put on a full block-policy channel, is a
  `chan_overflow` violation — raised in tier-1, counted in
  production while the shed/coalesce policies keep depth bounded.
- **SQL statement auditor** (round 16, armed via `store/sqlaudit.arm()`
  at install unless `SDTPU_SQL_AUDIT=off` — the runtime twin of
  sdlint's sql-discipline / tx-shape / schema-parity passes): every
  Database connection matches executed statements against the contract
  registry (store/statements.py). An undeclared statement outside the
  ad-hoc read allowance is `sql_undeclared`; a write-verb statement
  outside an open tx() is `sql_autocommit_write` — raised in tier-1,
  counted into `sd_sql_undeclared_total`/`sd_sanitize_violations_total`
  in production.
- **Fs auditor** (round 19, armed via `persist.arm()` at install
  unless `SDTPU_FS_AUDIT=off` — the runtime twin of sdlint's
  io-durability / crash-atomicity passes): os.replace/os.fsync are
  interposed; a raw product-module rename outside the declared
  persist seam is `persist_undeclared_write`, and a rename whose
  source was never fsynced against the artifact's declared policy is
  `persist_unfsynced_rename` — raised in tier-1, counted into
  `sd_persist_violations_total{kind}` in production.
- **Wire frame auditor** (round 20, armed via `p2p/wire.arm()` at
  install unless `SDTPU_WIRE_AUDIT=off` — the runtime twin of
  sdlint's wire-discipline / schema-drift / proto-compat passes):
  every frame crossing a tunnel in either direction is classified
  against the declared wire contracts (p2p/wire.py) — an undeclared
  kind, a schema mismatch, a size-cap breach, or a version skew is a
  `wire_violation` — raised in tier-1, counted into
  `sd_wire_violations_total{kind}` in production while conforming
  traffic feeds the `sd_wire_frames_total{name,dir}` census.
- **Cross-thread race recorder** (round 13, armed via
  `threadctx.arm()` at install unless `SDTPU_RACE_GUARD=off` — the
  runtime twin of sdlint's shared-mutation / thread-boundary /
  guard-consistency passes): every class declared in the
  threadctx.py ownership registry records (thread id, held
  tracked-lock set) per attribute/container write; one attribute
  written from two or more threads with an empty lockset
  intersection — or a second thread on a `loop_only`/`single_thread`
  attribute — is a `data_race` violation, raised in tier-1, counted
  into `sd_race_candidates_total{cls_attr}` in production.

Activation: `SDTPU_SANITIZE=1` + `install()` (tests/conftest.py calls
it for tier-1; node bootstrap may too). `SDTPU_SANITIZE_MODE=raise`
(tests) raises SanitizerViolation at the detection point where that is
safe (lock-order cycles); detections inside loop internals (stalls,
held-across-await) are always record-only and surface through
`violations()` — conftest asserts that list is empty at session end.
`count` mode (production) never raises: every detection increments
`sd_sanitize_violations_total{kind=...}` so /metrics and
`node.telemetry` expose them.

Disabled cost: `tracked_lock()` returns a plain `threading.Lock` and
`install()` is a no-op — zero overhead on every path.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Set

from . import flags
from .telemetry import SANITIZE_LOOP_MAX_STALL, SANITIZE_VIOLATIONS

__all__ = [
    "SanitizerViolation", "install", "installed", "uninstall",
    "tracked_lock", "tracked_rlock", "violations", "reset_violations",
    "held_tracked_locks", "held_tracked_lock_ids", "record",
]


class SanitizerViolation(RuntimeError):
    """Raised at the detection point in `raise` mode (safe sites only)."""


_installed = False
_mode = "count"
# Bounded: a long-lived count-mode node must not grow memory with its
# violation history — the full count lives in the telemetry counter;
# this list keeps the most recent details for violations()/tests.
_VIOLATIONS_CAP = 512
_violations: List[Dict[str, Any]] = []
_violations_lock = threading.Lock()
_orig_handle_run = None
_max_stall = 0.0

# Lock-order graph: graph id → graph ids acquired while it was held.
# Nodes are PER-INSTANCE (`name#seq`), not per-name: every Database
# names its locks db._write_lock/db._conns_lock, and a name-keyed graph
# would both miss cross-instance AB/BA deadlocks (libA.write vs
# libB.write taken in opposite orders reads as a reentrant skip) and
# merge unrelated instances' edges into false cycles.
# The lock graph IS the detector's memory: evicting edges would
# forget recorded orders and miss cycles. Bounded in practice by
# distinct tracked-lock instances (2 per Database); a pathological
# library-churn workload trades bytes for detection fidelity.
# sdlint: ok[unbounded-growth]
_edges: Dict[str, Set[str]] = {}
_edges_lock = threading.Lock()
_lock_seq = [0]

_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


def held_tracked_locks() -> List[str]:
    """Names of tracked locks the CALLING thread currently holds
    (outermost first) — the sanitizer's own introspection hook, also
    handy in tests."""
    return [lk.name for lk in _held_stack()]


def held_tracked_lock_ids() -> List[str]:
    """Per-INSTANCE graph ids (`name#seq`) of the calling thread's held
    tracked locks — the lockset the threadctx race recorder intersects
    across writer threads (names alone would merge distinct Database
    instances' locks into phantom protection)."""
    return [lk.graph_id for lk in _held_stack()]


def installed() -> bool:
    return _installed


def violations() -> List[Dict[str, Any]]:
    with _violations_lock:
        return list(_violations)


def reset_violations() -> None:
    with _violations_lock:
        _violations.clear()


# Incident-observatory hook (incidents.py set_violation_observer):
# notified per violation recorded WITHOUT raising — count mode is
# production, where a violation is otherwise one counter tick nobody
# saw; raise mode already hands the evidence to the raiser.
_violation_observer: Optional[Callable[[str, str], None]] = None


def set_violation_observer(
        cb: Optional[Callable[[str, str], None]]) -> None:
    global _violation_observer
    _violation_observer = cb


def _record(kind: str, detail: str, may_raise: bool) -> None:
    SANITIZE_VIOLATIONS.labels(kind=kind).inc()
    entry = {
        "kind": kind,
        "detail": detail,
        "thread": threading.current_thread().name,
        "stack": "".join(traceback.format_stack(limit=12)[:-2]),
    }
    with _violations_lock:
        _violations.append(entry)
        if len(_violations) > _VIOLATIONS_CAP:
            del _violations[0]
    if may_raise and _mode == "raise":
        raise SanitizerViolation(f"{kind}: {detail}")
    observer = _violation_observer
    if observer is not None:
        try:
            observer(kind, detail)
        except Exception:
            pass  # the black box must never break the detector


def record(kind: str, detail: str, may_raise: bool = False) -> None:
    """Public violation hook for the sanitizer's sibling runtimes
    (tasks.py's supervisor: `task_exception` / `task_orphaned`).
    Counts into sd_sanitize_violations_total and violations() whether
    or not install() ran — metrics must flow in production — and
    honors the raise/count split when asked (`may_raise`), exactly
    like the in-module detectors."""
    _record(kind, detail, may_raise=may_raise)


# -- lock-order recorder ----------------------------------------------------

def _would_cycle(new: str, held: List[str]) -> Optional[str]:
    """If acquiring `new` while `held` closes a cycle in the lock
    graph, return the offending held lock's name. DFS over recorded
    edges: a path new →* h means some thread acquires h after new —
    combined with this thread's h-then-new order, the AB/BA deadlock."""
    with _edges_lock:
        for h in held:
            if h == new:
                continue
            seen = {new}
            frontier = [new]
            while frontier:
                cur = frontier.pop()
                for nxt in _edges.get(cur, ()):
                    if nxt == h:
                        return h
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
    return None


def _note_acquire(lock: "_TrackedLock") -> None:
    held = [lk.graph_id for lk in _held_stack()
            if lk.graph_id != lock.graph_id]
    if not held:
        return
    offender = _would_cycle(lock.graph_id, held)
    if offender is not None:
        _record(
            "lock_order_cycle",
            f"acquiring {lock.graph_id!r} while holding {offender!r}, "
            f"but the recorded order elsewhere is {lock.graph_id!r} "
            f"before {offender!r}",
            may_raise=True)
    with _edges_lock:
        for h in held:
            _edges.setdefault(h, set()).add(lock.graph_id)


class _TrackedLock:
    """Order-recording wrapper with the threading.Lock surface the
    store uses (context manager + acquire/release + locked)."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = name
        with _edges_lock:
            _lock_seq[0] += 1
            self.graph_id = f"{name}#{_lock_seq[0]}"
        self._inner = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Order check BEFORE blocking: in raise mode the would-be
        # deadlock surfaces as an exception, not a hung suite.
        _note_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<tracked {type(self._inner).__name__} {self.name!r}>"


class _TrackedRLock(_TrackedLock):
    _factory = staticmethod(threading.RLock)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _held_stack()
        reentrant = any(lk is self for lk in stack)
        if not reentrant:
            _note_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            stack.append(self)
        return ok


def tracked_lock(name: str):
    """A lock-order-recorded Lock when the sanitizer is installed, a
    plain threading.Lock otherwise (zero overhead)."""
    return _TrackedLock(name) if _installed else threading.Lock()


def tracked_rlock(name: str):
    return _TrackedRLock(name) if _installed else threading.RLock()


# -- event-loop instrumentation --------------------------------------------

# Stall threshold, set at install() from SDTPU_SANITIZE_STALL_S;
# module-level so tests can tighten/loosen it after install.
_stall_s = 1.0


def _wrap_handle_run(orig):
    def _run(self):  # noqa: ANN001 — asyncio.events.Handle method
        global _max_stall
        t0 = time.perf_counter()
        try:
            return orig(self)
        finally:
            dt = time.perf_counter() - t0
            if dt > _max_stall:
                _max_stall = dt
                SANITIZE_LOOP_MAX_STALL.set(dt)
            if dt > _stall_s:
                # Never raise here: an exception out of Handle._run
                # lands in loop internals, not the offending code.
                _record(
                    "loop_stall",
                    f"event-loop callback ran {dt:.3f}s "
                    f"(threshold {_stall_s}s): {self!r}",
                    may_raise=False)
            held = held_tracked_locks()
            reported = getattr(_tls, "across_await_reported", None)
            if held:
                # The callback returned control to the loop with a
                # tracked lock held by the loop thread — a coroutine
                # suspended inside a critical section. Report each
                # lock ONCE per continuously-held episode: while the
                # offender stays suspended, every later (innocent)
                # callback would otherwise re-record it with a fresh
                # multi-KB stack.
                new = [n for n in held if not reported or n not in reported]
                if new:
                    _record(
                        "lock_across_await",
                        f"event-loop callback left lock(s) {new} held "
                        f"across a suspension point (first observed "
                        f"after: {self!r})",
                        may_raise=False)
                _tls.across_await_reported = set(held)
            elif reported:
                _tls.across_await_reported = None
    return _run


def install() -> bool:
    """Arm the sanitizer if SDTPU_SANITIZE is set. Idempotent; returns
    whether the sanitizer is installed after the call. Locks created
    BEFORE install are plain locks — install early (conftest import,
    node bootstrap) so the store's locks come from tracked_lock."""
    global _installed, _mode, _orig_handle_run, _stall_s
    if _installed:
        return True
    if not flags.get("SDTPU_SANITIZE"):
        return False
    _mode = flags.get("SDTPU_SANITIZE_MODE")
    _stall_s = flags.get("SDTPU_SANITIZE_STALL_S")
    import asyncio.events

    _orig_handle_run = asyncio.events.Handle._run
    asyncio.events.Handle._run = _wrap_handle_run(_orig_handle_run)
    # Arm the device-layer twin: jit retrace counting against the
    # declared budgets and the D2H transfer guard inside
    # device_scope()/io() regions (ops/jit_registry.py). Same
    # raise/count split; violations flow through _record into the
    # shared list + sd_sanitize_violations_total.
    from .ops import jit_registry

    jit_registry.arm(_mode, _record)
    # Arm the resource-layer twin: channel depth-watermark breaches
    # (channels.py) flow through _record as `chan_overflow`.
    from . import channels

    channels.arm(_mode, _record)
    # Arm the thread-safety twin: declared owner classes record
    # (thread id, held lockset) per write; contract breaches flow
    # through _record as `data_race`. SDTPU_RACE_GUARD=off skips the
    # wrap entirely (threadctx checks it — read once, at install).
    from . import threadctx

    threadctx.arm(_mode, _record, held_tracked_lock_ids)
    # Arm the store twin: every Database connection created from here
    # on is contract-audited against store/statements.py — undeclared
    # statements and autocommit writes flow through _record as
    # `sql_undeclared` / `sql_autocommit_write`. SDTPU_SQL_AUDIT=off
    # skips the wrap (sqlaudit checks it — read once, at install).
    from .store import sqlaudit

    sqlaudit.arm(_mode, _record)
    # Arm the durability twin: the fs auditor interposes
    # os.replace/os.fsync and judges every rename against the persist
    # registry's declared fsync policies — breaches flow through
    # _record as `persist_undeclared_write` / `persist_unfsynced_`
    # `rename`. SDTPU_FS_AUDIT=off skips the wrap (persist checks it
    # — read once, at install).
    from . import persist

    persist.arm(_mode, _record)
    # Arm the protocol twin: the tunnel seam classifies + validates
    # every frame against the declared wire contracts — breaches flow
    # through _record as `wire_violation`. SDTPU_WIRE_AUDIT=off skips
    # the arming (wire checks it — read once, at install); pack/unpack
    # validate regardless.
    from .p2p import wire

    wire.arm(_mode, _record)
    _installed = True
    return True


def uninstall() -> None:
    """Disarm (tests). Already-created tracked locks keep recording
    into the (now-idle) graph; new ones are plain again."""
    global _installed, _orig_handle_run
    if not _installed:
        return
    import asyncio.events

    if _orig_handle_run is not None:
        asyncio.events.Handle._run = _orig_handle_run
        _orig_handle_run = None
    from .ops import jit_registry

    jit_registry.disarm()
    from . import channels

    channels.disarm()
    from . import threadctx

    threadctx.disarm()
    from .store import sqlaudit

    sqlaudit.disarm()
    from . import persist

    persist.disarm()
    from .p2p import wire

    wire.disarm()
    _installed = False
