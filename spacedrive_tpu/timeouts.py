"""Central network-await timeout registry — the lifecycle twin of
ops/jit_registry.py's contract table.

Every await on a socket/tunnel/ws frame in `p2p/`, `api/`, `sync/`
runs under a budget DECLARED here — name, default seconds, and a
docstring — and applied through `with_timeout(name, awaitable)` or a
`deadline(name)` block. Scattered `asyncio.wait_for(..., 30)` literals
made the hang surface unauditable (a peer that stops acking a clone
page parked the originator forever; the spacedrop verdict wait was the
only network await with ANY budget); tools/sdlint's timeout-discipline
pass now fails the build on a network-root await that is not covered
by a declared budget, and on a `with_timeout` name missing from this
table.

Effective budget = declared default × `SDTPU_TIMEOUT_SCALE`
(flags.py): thin-pipe or debug hosts scale every budget at once
instead of chasing literals. A fired budget counts into
`sd_timeout_fired_total{name}` before the TimeoutError propagates —
/metrics shows WHICH contract is tripping in production.

README's timeout table is generated from this registry
(`python -m tools.sdlint --timeout-table`).

Design constraints (same as flags.py): stdlib + flags/telemetry only,
importable from every layer without cycles.

Budget ordering invariants (asserted nowhere, documented here):
`p2p.spacedrop.verdict` must EXCEED `p2p.spacedrop.decide` — the
sender's verdict wait brackets the receiver's interactive decision
window; equal budgets would race the legitimate decide path.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple, Type

from . import flags
from .telemetry import BACKOFF_GAVE_UP, BACKOFF_RETRIES, TIMEOUTS_FIRED

__all__ = [
    "TimeoutContract", "TIMEOUTS", "declare_timeout", "budget",
    "with_timeout", "deadline", "timeout_table_markdown",
    "BackoffContract", "BACKOFFS", "declare_backoff", "Backoff",
    "with_backoff", "RetrySchedule", "backoff_table_markdown",
]


@dataclass(frozen=True)
class TimeoutContract:
    name: str        # dotted id: "<layer>.<operation>"
    default_s: float
    doc: str


TIMEOUTS: Dict[str, TimeoutContract] = {}


def declare_timeout(name: str, default_s: float, doc: str
                    ) -> TimeoutContract:
    if name in TIMEOUTS:
        raise ValueError(f"timeout {name!r} declared twice")
    if default_s <= 0:
        raise ValueError(f"timeout {name!r}: budget must be positive")
    c = TimeoutContract(name, float(default_s), doc)
    TIMEOUTS[name] = c
    return c


def budget(name: str) -> float:
    """Effective seconds for a declared budget. An unknown name is a
    programming error, not a lookup miss — exactly flags.raw()."""
    c = TIMEOUTS.get(name)
    if c is None:
        raise KeyError(f"undeclared timeout {name!r} (declare it in "
                       "spacedrive_tpu/timeouts.py)")
    return c.default_s * flags.get("SDTPU_TIMEOUT_SCALE")


async def with_timeout(name: str, awaitable: Awaitable) -> Any:
    """`asyncio.wait_for` under a declared budget; a fired budget
    counts into sd_timeout_fired_total{name} before raising."""
    try:
        return await asyncio.wait_for(awaitable, budget(name))
    except asyncio.TimeoutError:
        TIMEOUTS_FIRED.labels(name=name).inc()
        raise


class _Deadline:
    """Block-scoped budget for multi-await sequences (handshakes,
    pair round-trips): schedules a cancel at the budget and converts
    the resulting CancelledError back into asyncio.TimeoutError at the
    block edge. Python 3.10 has no asyncio.timeout(); this is the same
    cancel-at-deadline shape (and shares its pre-3.11 edge: a timer
    firing in the instant between the block's last await and __aexit__
    still raises TimeoutError, but the task-level cancel may surface
    at the caller's next await — budgets here are tens of seconds over
    millisecond blocks, so the window is vanishing)."""

    def __init__(self, name: str):
        self.name = name
        self._fired = False
        self._handle: Optional[asyncio.TimerHandle] = None
        self._task: Optional[asyncio.Task] = None

    def _fire(self) -> None:
        self._fired = True
        if self._task is not None and not self._task.done():
            self._task.cancel()

    async def __aenter__(self) -> "_Deadline":
        self._task = asyncio.current_task()
        self._handle = asyncio.get_running_loop().call_later(
            budget(self.name), self._fire)
        return self

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if self._handle is not None:
            self._handle.cancel()
        if self._fired and exc_type in (None, asyncio.CancelledError):
            if exc_type is None and self._task is not None:
                # The timer fired in the window between the block's
                # last await completing and __aexit__: our cancel is
                # PENDING (no suspension point saw it). Neutralize it
                # (best-effort — CPython parks it in _must_cancel) so
                # the deterministic TimeoutError below is the only
                # consequence, not a surprise CancelledError at the
                # caller's next unrelated await.
                if getattr(self._task, "_must_cancel", False):
                    self._task._must_cancel = False
            TIMEOUTS_FIRED.labels(name=self.name).inc()
            raise asyncio.TimeoutError(
                f"deadline {self.name!r} "
                f"({budget(self.name)}s) exceeded") from exc
        return False


def deadline(name: str) -> _Deadline:
    """``async with deadline("p2p.handshake"):`` — every await inside
    the block shares the named budget. sdlint's timeout-discipline
    pass treats the block as covered."""
    return _Deadline(name)


def timeout_table_markdown() -> str:
    """README's generated timeout table (one row per declared budget)."""
    out = ["| Budget | Default | Covers |", "| --- | --- | --- |"]
    for name in sorted(TIMEOUTS):
        c = TIMEOUTS[name]
        doc = " ".join(c.doc.split())
        out.append(f"| `{name}` | {c.default_s:g}s | {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Declared retry/backoff policies — the recovery twin of the budget
# table above. Before this registry the tree's retry loops were bare
# `P2P_RECONNECTS.inc(); continue` shapes: fixed-interval hammering
# with no ladder, no jitter, no give-up, and no way for a chaos test
# to pin the discipline. Every retrying path now names a policy
# declared here; each scheduled retry counts into
# sd_backoff_retries_total{name} and an exhausted ladder into
# sd_backoff_gave_up_total{name}.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BackoffContract:
    name: str          # dotted id: "<layer>.<operation>"
    base_s: float      # first retry delay
    cap_s: float       # ladder ceiling
    factor: float      # multiplier per retry
    jitter: float      # ± fraction of the delay (thundering-herd break)
    max_tries: int     # retries before give-up; 0 = retry forever
    doc: str


BACKOFFS: Dict[str, BackoffContract] = {}

# Incident-observatory hook (incidents.py set_give_up_observer):
# notified once per exhausted ladder, exactly when
# sd_backoff_gave_up_total counts it — a give-up means an operation
# stopped retrying and degraded, which is a postmortem moment.
_give_up_observer: Optional[Callable[[str, int], None]] = None


def set_give_up_observer(
        cb: Optional[Callable[[str, int], None]]) -> None:
    global _give_up_observer
    _give_up_observer = cb


def declare_backoff(name: str, base_s: float, cap_s: float,
                    factor: float, jitter: float, max_tries: int,
                    doc: str) -> BackoffContract:
    if name in BACKOFFS:
        raise ValueError(f"backoff {name!r} declared twice")
    if base_s <= 0 or cap_s < base_s:
        raise ValueError(f"backoff {name!r}: want 0 < base <= cap")
    if factor < 1.0:
        raise ValueError(f"backoff {name!r}: factor must be >= 1")
    if not 0.0 <= jitter < 1.0:
        raise ValueError(f"backoff {name!r}: jitter must be in [0, 1)")
    if max_tries < 0:
        raise ValueError(f"backoff {name!r}: max_tries must be >= 0")
    c = BackoffContract(name, float(base_s), float(cap_s),
                        float(factor), float(jitter), int(max_tries),
                        doc)
    BACKOFFS[name] = c
    return c


class Backoff:
    """One failing operation's ladder state for a declared policy.

    `next_delay()` is called on each failure: it returns the jittered
    delay to wait before the next try (counting the retry), or None
    when the ladder is exhausted (counting the give-up) — the caller
    stops retrying and degrades. `reset()` on success so an
    intermittent peer climbs down. Deterministic under a seeded `rng`
    (what the chaos tests pin); the default shares `random`'s global
    stream, which is jitter's whole job."""

    def __init__(self, name: str,
                 rng: Optional[random.Random] = None):
        c = BACKOFFS.get(name)
        if c is None:
            raise KeyError(f"undeclared backoff {name!r} (declare it "
                           "in spacedrive_tpu/timeouts.py)")
        self.contract = c
        self.tries = 0
        self._gave_up_counted = False
        self._rng = rng
        self._m_retry = BACKOFF_RETRIES.labels(name=name)
        self._m_gave_up = BACKOFF_GAVE_UP.labels(name=name)

    def next_delay(self) -> Optional[float]:
        c = self.contract
        if c.max_tries and self.tries >= c.max_tries:
            # Counted ONCE per exhausted ladder, not once per call:
            # RetrySchedule keeps probing a given-up key at the cap
            # cadence, and each probe failure landing here must not
            # re-count the same outage (the counter means "ladders
            # exhausted", and the hand-off it documents fires once).
            if not self._gave_up_counted:
                # Same per-instance single-thread contract as `tries`
                # above (threadctx.py; the armed recorder audits it).
                self._gave_up_counted = True  # sdlint: ok[shared-mutation]
                self._m_gave_up.inc()
                observer = _give_up_observer
                if observer is not None:
                    try:
                        observer(c.name, self.tries)
                    except Exception:
                        pass  # black box never breaks the ladder
            return None
        # Exponent clamped: an unbounded ladder (max_tries 0) parked
        # at the cap for days would otherwise drive factor**tries past
        # float range and raise OverflowError out of a poll loop.
        d = min(c.cap_s, c.base_s * (c.factor ** min(self.tries, 64)))
        # Writers span loop+worker contexts across INSTANCES, never on
        # one instance: each ladder is strictly per-use-site (contract
        # in threadctx.py; the armed race recorder audits it).
        self.tries += 1  # sdlint: ok[shared-mutation]
        if c.jitter:
            r = (self._rng.random() if self._rng is not None
                 else random.random())
            d *= 1.0 + c.jitter * (2.0 * r - 1.0)
        d *= flags.get("SDTPU_TIMEOUT_SCALE")
        self._m_retry.inc()
        return d

    def exhausted(self) -> bool:
        c = self.contract
        return bool(c.max_tries) and self.tries >= c.max_tries

    def reset(self) -> None:
        self.tries = 0
        self._gave_up_counted = False


async def with_backoff(name: str, fn: Callable[[], Awaitable],
                       retry_on: Tuple[Type[BaseException], ...]
                       = (ConnectionError, OSError,
                          asyncio.TimeoutError),
                       rng: Optional[random.Random] = None) -> Any:
    """Call `fn()` under the declared policy: a retryable failure
    sleeps the ladder's next jittered delay and tries again; an
    exhausted ladder re-raises the final failure (after counting the
    give-up). CancelledError always propagates."""
    b = Backoff(name, rng=rng)
    while True:
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except retry_on:
            d = b.next_delay()
            if d is None:
                raise
            await asyncio.sleep(d)


class RetrySchedule:
    """Per-key backoff bookkeeping for POLL-shaped loops (the sync
    announcer's peer fan-out, the fleet poller's round): the loop
    itself keeps ticking, and this schedule answers "is `key` allowed
    an attempt right now?" from each key's private ladder.

    `failure(key)` advances the ladder and returns the delay until the
    key's next allowed attempt — or None when the ladder just gave up
    (the caller hands the key off: the announcer marks the peer stale
    with the fleet observatory). A given-up key stays parked at the
    policy cap (it is retried again, at cap cadence, so a healed peer
    is eventually found without hammering a dead one). `success(key)`
    evicts the key's state entirely — the maps are bounded by
    currently-failing keys, not history."""

    def __init__(self, name: str,
                 rng: Optional[random.Random] = None):
        self.name = name
        self.contract = BACKOFFS[name] if name in BACKOFFS else None
        if self.contract is None:
            raise KeyError(f"undeclared backoff {name!r} (declare it "
                           "in spacedrive_tpu/timeouts.py)")
        self._rng = rng
        self._ladders: Dict[Any, Backoff] = {}
        self._retry_at: Dict[Any, float] = {}

    def allowed(self, key: Any, now: Optional[float] = None) -> bool:
        t = time.monotonic() if now is None else now
        return t >= self._retry_at.get(key, 0.0)

    def failure(self, key: Any, now: Optional[float] = None
                ) -> Optional[float]:
        t = time.monotonic() if now is None else now
        b = self._ladders.get(key)
        if b is None:
            b = self._ladders[key] = Backoff(self.name, rng=self._rng)
        d = b.next_delay()
        if d is None:
            # Gave up: park at the cap — cap-cadence probing finds a
            # healed peer eventually; the caller does the hand-off.
            self._retry_at[key] = t + self.contract.cap_s * \
                flags.get("SDTPU_TIMEOUT_SCALE")
            return None
        self._retry_at[key] = t + d
        return d

    def gave_up(self, key: Any) -> bool:
        b = self._ladders.get(key)
        return b is not None and b.exhausted()

    def success(self, key: Any) -> None:
        self._ladders.pop(key, None)
        self._retry_at.pop(key, None)

    def evict(self, key: Any) -> None:
        self.success(key)


def backoff_table_markdown() -> str:
    """README's generated backoff table (one row per declared
    policy)."""
    out = ["| Policy | Base | Cap | Factor | Jitter | Max tries "
           "| Covers |",
           "| --- | --- | --- | --- | --- | --- | --- |"]
    for name in sorted(BACKOFFS):
        c = BACKOFFS[name]
        doc = " ".join(c.doc.split())
        tries = str(c.max_tries) if c.max_tries else "∞"
        out.append(
            f"| `{name}` | {c.base_s:g}s | {c.cap_s:g}s | "
            f"×{c.factor:g} | ±{c.jitter:.0%} | {tries} | {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE budget namespace. Keep alphabetical within each layer; every
# entry is enforced by the sdlint timeout-discipline pass (a network
# await outside a declared budget fails the build).
# ---------------------------------------------------------------------------

# -- api (rspc HTTP + websocket host) ---------------------------------------

declare_timeout(
    "api.http.read", 30.0,
    "Reading a request body (rspc POST input JSON): bounds a "
    "slow-loris client on the API host.")

declare_timeout(
    "api.http.write", 60.0,
    "One streamed response chunk (thumbnail/file/static serving): a "
    "stalled client releases the handler instead of pinning it.")

declare_timeout(
    "api.ws.prepare", 30.0,
    "Websocket upgrade handshake on the rspc ws route.")

declare_timeout(
    "api.ws.send", 30.0,
    "One websocket frame to a subscriber (responses, subscription "
    "events): a dead client cannot wedge the emit path.")

# -- bench (tools-only put budgets; not wire awaits) ------------------------

declare_timeout(
    "bench.chan.put", 5.0,
    "tools/chan_bench.py producer's bounded put on the block-policy "
    "bench channel — the measured put-block path.")

declare_timeout(
    "bench.load.wire.put", 60.0,
    "tools/load_bench.py stub-transport frame put: bounds a simulated "
    "peer whose consumer half wedged, mirroring the TCP plane's "
    "drain deadlines.")

# -- fleet (cross-node observability federation) ----------------------------

declare_timeout(
    "fleet.poll", 15.0,
    "One whole obs.health/obs.metrics fetch from a paired peer "
    "(fleet.py poll round): connect + request + response, any "
    "transport. A hung peer costs the poller this budget, then its "
    "row goes stale-degraded.")

declare_timeout(
    "fleet.trace.fetch", 60.0,
    "One peer's obs.trace slice during distributed trace assembly "
    "(fleet.py assemble_trace): span-ring + timeline copies are "
    "bigger than health snapshots, so the budget is too.")

# -- ops (device-pipeline put budgets; not wire awaits) ---------------------

declare_timeout(
    "ops.pipeline.inflight.put", 600.0,
    "Depth-N identify pipeline dispatcher waiting for the retirer to "
    "drain the in-flight window (channels.py ops.pipeline.inflight): "
    "a wedged D2H fetch frees the dispatcher here instead of parking "
    "the device stream forever. Sized for thin-tunnel H2D weather at "
    "bench batch sizes.")

declare_timeout(
    "ops.pipeline.staged.put", 600.0,
    "Depth-N identify pipeline stager waiting for a dispatcher to "
    "drain the staged-batch channel (channels.py ops.pipeline.staged) "
    "— the backpressure edge when H2D or the kernel is the bottleneck.")

# -- p2p (tunnel control plane) ---------------------------------------------

declare_timeout(
    "p2p.connect", 20.0,
    "Outbound TCP dial + authenticated tunnel handshake "
    "(P2PManager.open_stream).")

declare_timeout(
    "p2p.file.response", 60.0,
    "The remote library's file-request decision frame "
    "(request_file's status/req header).")

declare_timeout(
    "p2p.frame_send", 60.0,
    "One control/ops frame into a tunnel including the drain "
    "backpressure wait — a receiver that stops reading frees the "
    "sender here.")

declare_timeout(
    "p2p.handshake", 20.0,
    "The signed-ephemeral key exchange on a fresh tunnel "
    "(proto.tunnel_handshake, both roles).")

declare_timeout(
    "p2p.header_recv", 30.0,
    "Inbound dispatch header after an accepted handshake: a silent "
    "dialer cannot hold a server slot open.")

declare_timeout(
    "p2p.obs", 30.0,
    "One obs.metrics/obs.health/obs.trace exchange on a tunnel "
    "(p2p/obs.py P2PObsClient and the manager's serving side): the "
    "request frame, the snapshot-building, and the response frame "
    "all inside one budget.")

declare_timeout(
    "p2p.pair", 60.0,
    "The whole pairing round-trip (instance-row exchange incl. the "
    "responder's DB writes).")

declare_timeout(
    "p2p.ping", 20.0,
    "Ping round-trip over a fresh tunnel.")

declare_timeout(
    "p2p.spacedrop.decide", 60.0,
    "Interactive accept/reject window for an inbound spacedrop offer "
    "(the reference's 60s prompt).")

declare_timeout(
    "p2p.spacedrop.verdict", 75.0,
    "Sender's wait for the receiver's accept/reject — brackets the "
    "receiver's full p2p.spacedrop.decide window, so it MUST stay "
    "longer than it.")

declare_timeout(
    "p2p.transfer.chunk", 60.0,
    "One spaceblock block (send or receive) plus its ack: transfers "
    "of any size stay live as long as per-block progress continues.")

# -- sync (CRDT pull + clone fast path) -------------------------------------

declare_timeout(
    "sync.clone.ack", 180.0,
    "Originator's wait for one blob-page watermark ack — covers the "
    "receiver's batched one-tx page apply at bulk page sizes.")

declare_timeout(
    "sync.clone.ack_send", 60.0,
    "Receiver pushing a page ack back up the tunnel.")

declare_timeout(
    "sync.clone.drain", 120.0,
    "Flushing a pipelined clone window into the socket against a "
    "slow receiver's backpressure.")

declare_timeout(
    "sync.clone.frame", 180.0,
    "Receiver's wait for the next clone-stream frame (page, "
    "interleaved ops, or blob_done) from the originator.")

declare_timeout(
    "sync.clone.serve", 120.0,
    "One clone-serve page-fetch slot on the fair-share gate "
    "(channels.py sync.clone.serve): with many peers cloning "
    "concurrently, each stream's next page fetch waits its FIFO turn "
    "here instead of letting a hot stream monopolize the executor — "
    "a wait past this budget means the node is clone-overcommitted.")

declare_timeout(
    "sync.ingest.backlog", 180.0,
    "Ingester waiting for space in its bounded request channel "
    "(channels.py sync.ingest.requests): the _pull consumer drains it "
    "between wire frames, so a wedged consumer frees the actor here "
    "instead of parking it forever.")

declare_timeout(
    "sync.pull.page", 180.0,
    "Responder's wait for one ops page — the originator runs get_ops "
    "off-loop over bulk op logs before answering.")

declare_timeout(
    "sync.pull.request", 180.0,
    "Originator's wait for the responder's next pull request — the "
    "responder ingests the previous page (one tx per page) before "
    "asking again.")

# -- store (single-writer group-commit actor) -------------------------------

declare_timeout(
    "store.actor.put", 30.0,
    "A writer waiting for space in the storage actor's bounded batch "
    "queue (channels.py store.actor.queue): the write-path admission "
    "edge — a wedged writer thread frees its producers here instead "
    "of parking every job forever.")

declare_timeout(
    "store.actor.write", 600.0,
    "A writer's whole trip through the group-commit actor "
    "(store/actor.py): grant wait + every batch body coalesced ahead "
    "of it + the group's COMMIT. Sized for bulk-chunk batch bodies "
    "(a 4096-file indexer chunk riding the same group); firing means "
    "the writer thread is wedged, not slow.")


# ---------------------------------------------------------------------------
# THE backoff namespace. Keep alphabetical within each layer; every
# retrying loop in the tree must name a policy here (the bare
# fixed-interval retry is the shape this registry retired).
# ---------------------------------------------------------------------------

declare_backoff(
    "fleet.peer.poll", 10.0, 300.0, 2.0, 0.25, 0,
    "Fleet-observatory polling of an UNREACHABLE peer (fleet.py): "
    "after a failed obs.health fetch the peer's next poll waits this "
    "ladder instead of burning a fleet.poll budget every round; "
    "max_tries 0 = never gives up (the row is already stale-degraded; "
    "cap-cadence probing notices the heal).")

declare_backoff(
    "obs.http", 0.2, 2.0, 2.0, 0.25, 3,
    "HttpObsClient fetch retries (fleet.py): transient connect "
    "failures against a restarting peer retry inside the caller's "
    "fleet.poll budget; exhaustion surfaces the final error to the "
    "poller, which marks the row unreachable.")

declare_backoff(
    "p2p.announce.reconnect", 0.5, 60.0, 2.0, 0.25, 6,
    "Sync announce fan-out to a peer that failed its last round "
    "(p2p/sync_net.py originate): a flapping peer is retried up this "
    "ladder instead of being hammered on every local write; "
    "exhaustion hands the peer to the fleet observatory as a stale "
    "row and parks retries at the cap until it heals (peers pull on "
    "reconnect regardless).")

declare_backoff(
    "store.busy", 0.05, 1.0, 2.0, 0.25, 5,
    "Write-transaction commit retry on sqlite BUSY (store/db.py tx): "
    "an external writer holding the file lock — or an injected "
    "store.commit chaos fault — degrades to bounded latency "
    "(sd_store_busy_retries_total) instead of failing the job; "
    "exhaustion re-raises the BUSY.")
