"""Central network-await timeout registry — the lifecycle twin of
ops/jit_registry.py's contract table.

Every await on a socket/tunnel/ws frame in `p2p/`, `api/`, `sync/`
runs under a budget DECLARED here — name, default seconds, and a
docstring — and applied through `with_timeout(name, awaitable)` or a
`deadline(name)` block. Scattered `asyncio.wait_for(..., 30)` literals
made the hang surface unauditable (a peer that stops acking a clone
page parked the originator forever; the spacedrop verdict wait was the
only network await with ANY budget); tools/sdlint's timeout-discipline
pass now fails the build on a network-root await that is not covered
by a declared budget, and on a `with_timeout` name missing from this
table.

Effective budget = declared default × `SDTPU_TIMEOUT_SCALE`
(flags.py): thin-pipe or debug hosts scale every budget at once
instead of chasing literals. A fired budget counts into
`sd_timeout_fired_total{name}` before the TimeoutError propagates —
/metrics shows WHICH contract is tripping in production.

README's timeout table is generated from this registry
(`python -m tools.sdlint --timeout-table`).

Design constraints (same as flags.py): stdlib + flags/telemetry only,
importable from every layer without cycles.

Budget ordering invariants (asserted nowhere, documented here):
`p2p.spacedrop.verdict` must EXCEED `p2p.spacedrop.decide` — the
sender's verdict wait brackets the receiver's interactive decision
window; equal budgets would race the legitimate decide path.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Awaitable, Dict, Optional

from . import flags
from .telemetry import TIMEOUTS_FIRED

__all__ = [
    "TimeoutContract", "TIMEOUTS", "declare_timeout", "budget",
    "with_timeout", "deadline", "timeout_table_markdown",
]


@dataclass(frozen=True)
class TimeoutContract:
    name: str        # dotted id: "<layer>.<operation>"
    default_s: float
    doc: str


TIMEOUTS: Dict[str, TimeoutContract] = {}


def declare_timeout(name: str, default_s: float, doc: str
                    ) -> TimeoutContract:
    if name in TIMEOUTS:
        raise ValueError(f"timeout {name!r} declared twice")
    if default_s <= 0:
        raise ValueError(f"timeout {name!r}: budget must be positive")
    c = TimeoutContract(name, float(default_s), doc)
    TIMEOUTS[name] = c
    return c


def budget(name: str) -> float:
    """Effective seconds for a declared budget. An unknown name is a
    programming error, not a lookup miss — exactly flags.raw()."""
    c = TIMEOUTS.get(name)
    if c is None:
        raise KeyError(f"undeclared timeout {name!r} (declare it in "
                       "spacedrive_tpu/timeouts.py)")
    return c.default_s * flags.get("SDTPU_TIMEOUT_SCALE")


async def with_timeout(name: str, awaitable: Awaitable) -> Any:
    """`asyncio.wait_for` under a declared budget; a fired budget
    counts into sd_timeout_fired_total{name} before raising."""
    try:
        return await asyncio.wait_for(awaitable, budget(name))
    except asyncio.TimeoutError:
        TIMEOUTS_FIRED.labels(name=name).inc()
        raise


class _Deadline:
    """Block-scoped budget for multi-await sequences (handshakes,
    pair round-trips): schedules a cancel at the budget and converts
    the resulting CancelledError back into asyncio.TimeoutError at the
    block edge. Python 3.10 has no asyncio.timeout(); this is the same
    cancel-at-deadline shape (and shares its pre-3.11 edge: a timer
    firing in the instant between the block's last await and __aexit__
    still raises TimeoutError, but the task-level cancel may surface
    at the caller's next await — budgets here are tens of seconds over
    millisecond blocks, so the window is vanishing)."""

    def __init__(self, name: str):
        self.name = name
        self._fired = False
        self._handle: Optional[asyncio.TimerHandle] = None
        self._task: Optional[asyncio.Task] = None

    def _fire(self) -> None:
        self._fired = True
        if self._task is not None and not self._task.done():
            self._task.cancel()

    async def __aenter__(self) -> "_Deadline":
        self._task = asyncio.current_task()
        self._handle = asyncio.get_running_loop().call_later(
            budget(self.name), self._fire)
        return self

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if self._handle is not None:
            self._handle.cancel()
        if self._fired and exc_type in (None, asyncio.CancelledError):
            if exc_type is None and self._task is not None:
                # The timer fired in the window between the block's
                # last await completing and __aexit__: our cancel is
                # PENDING (no suspension point saw it). Neutralize it
                # (best-effort — CPython parks it in _must_cancel) so
                # the deterministic TimeoutError below is the only
                # consequence, not a surprise CancelledError at the
                # caller's next unrelated await.
                if getattr(self._task, "_must_cancel", False):
                    self._task._must_cancel = False
            TIMEOUTS_FIRED.labels(name=self.name).inc()
            raise asyncio.TimeoutError(
                f"deadline {self.name!r} "
                f"({budget(self.name)}s) exceeded") from exc
        return False


def deadline(name: str) -> _Deadline:
    """``async with deadline("p2p.handshake"):`` — every await inside
    the block shares the named budget. sdlint's timeout-discipline
    pass treats the block as covered."""
    return _Deadline(name)


def timeout_table_markdown() -> str:
    """README's generated timeout table (one row per declared budget)."""
    out = ["| Budget | Default | Covers |", "| --- | --- | --- |"]
    for name in sorted(TIMEOUTS):
        c = TIMEOUTS[name]
        doc = " ".join(c.doc.split())
        out.append(f"| `{name}` | {c.default_s:g}s | {doc} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE budget namespace. Keep alphabetical within each layer; every
# entry is enforced by the sdlint timeout-discipline pass (a network
# await outside a declared budget fails the build).
# ---------------------------------------------------------------------------

# -- api (rspc HTTP + websocket host) ---------------------------------------

declare_timeout(
    "api.http.read", 30.0,
    "Reading a request body (rspc POST input JSON): bounds a "
    "slow-loris client on the API host.")

declare_timeout(
    "api.http.write", 60.0,
    "One streamed response chunk (thumbnail/file/static serving): a "
    "stalled client releases the handler instead of pinning it.")

declare_timeout(
    "api.ws.prepare", 30.0,
    "Websocket upgrade handshake on the rspc ws route.")

declare_timeout(
    "api.ws.send", 30.0,
    "One websocket frame to a subscriber (responses, subscription "
    "events): a dead client cannot wedge the emit path.")

# -- bench (tools-only put budgets; not wire awaits) ------------------------

declare_timeout(
    "bench.chan.put", 5.0,
    "tools/chan_bench.py producer's bounded put on the block-policy "
    "bench channel — the measured put-block path.")

# -- fleet (cross-node observability federation) ----------------------------

declare_timeout(
    "fleet.poll", 15.0,
    "One whole obs.health/obs.metrics fetch from a paired peer "
    "(fleet.py poll round): connect + request + response, any "
    "transport. A hung peer costs the poller this budget, then its "
    "row goes stale-degraded.")

declare_timeout(
    "fleet.trace.fetch", 60.0,
    "One peer's obs.trace slice during distributed trace assembly "
    "(fleet.py assemble_trace): span-ring + timeline copies are "
    "bigger than health snapshots, so the budget is too.")

# -- ops (device-pipeline put budgets; not wire awaits) ---------------------

declare_timeout(
    "ops.pipeline.inflight.put", 600.0,
    "Depth-N identify pipeline dispatcher waiting for the retirer to "
    "drain the in-flight window (channels.py ops.pipeline.inflight): "
    "a wedged D2H fetch frees the dispatcher here instead of parking "
    "the device stream forever. Sized for thin-tunnel H2D weather at "
    "bench batch sizes.")

declare_timeout(
    "ops.pipeline.staged.put", 600.0,
    "Depth-N identify pipeline stager waiting for a dispatcher to "
    "drain the staged-batch channel (channels.py ops.pipeline.staged) "
    "— the backpressure edge when H2D or the kernel is the bottleneck.")

# -- p2p (tunnel control plane) ---------------------------------------------

declare_timeout(
    "p2p.connect", 20.0,
    "Outbound TCP dial + authenticated tunnel handshake "
    "(P2PManager.open_stream).")

declare_timeout(
    "p2p.file.response", 60.0,
    "The remote library's file-request decision frame "
    "(request_file's status/req header).")

declare_timeout(
    "p2p.frame_send", 60.0,
    "One control/ops frame into a tunnel including the drain "
    "backpressure wait — a receiver that stops reading frees the "
    "sender here.")

declare_timeout(
    "p2p.handshake", 20.0,
    "The signed-ephemeral key exchange on a fresh tunnel "
    "(proto.tunnel_handshake, both roles).")

declare_timeout(
    "p2p.header_recv", 30.0,
    "Inbound dispatch header after an accepted handshake: a silent "
    "dialer cannot hold a server slot open.")

declare_timeout(
    "p2p.obs", 30.0,
    "One obs.metrics/obs.health/obs.trace exchange on a tunnel "
    "(p2p/obs.py P2PObsClient and the manager's serving side): the "
    "request frame, the snapshot-building, and the response frame "
    "all inside one budget.")

declare_timeout(
    "p2p.pair", 60.0,
    "The whole pairing round-trip (instance-row exchange incl. the "
    "responder's DB writes).")

declare_timeout(
    "p2p.ping", 20.0,
    "Ping round-trip over a fresh tunnel.")

declare_timeout(
    "p2p.spacedrop.decide", 60.0,
    "Interactive accept/reject window for an inbound spacedrop offer "
    "(the reference's 60s prompt).")

declare_timeout(
    "p2p.spacedrop.verdict", 75.0,
    "Sender's wait for the receiver's accept/reject — brackets the "
    "receiver's full p2p.spacedrop.decide window, so it MUST stay "
    "longer than it.")

declare_timeout(
    "p2p.transfer.chunk", 60.0,
    "One spaceblock block (send or receive) plus its ack: transfers "
    "of any size stay live as long as per-block progress continues.")

# -- sync (CRDT pull + clone fast path) -------------------------------------

declare_timeout(
    "sync.clone.ack", 180.0,
    "Originator's wait for one blob-page watermark ack — covers the "
    "receiver's batched one-tx page apply at bulk page sizes.")

declare_timeout(
    "sync.clone.ack_send", 60.0,
    "Receiver pushing a page ack back up the tunnel.")

declare_timeout(
    "sync.clone.drain", 120.0,
    "Flushing a pipelined clone window into the socket against a "
    "slow receiver's backpressure.")

declare_timeout(
    "sync.clone.frame", 180.0,
    "Receiver's wait for the next clone-stream frame (page, "
    "interleaved ops, or blob_done) from the originator.")

declare_timeout(
    "sync.ingest.backlog", 180.0,
    "Ingester waiting for space in its bounded request channel "
    "(channels.py sync.ingest.requests): the _pull consumer drains it "
    "between wire frames, so a wedged consumer frees the actor here "
    "instead of parking it forever.")

declare_timeout(
    "sync.pull.page", 180.0,
    "Responder's wait for one ops page — the originator runs get_ops "
    "off-loop over bulk op logs before answering.")

declare_timeout(
    "sync.pull.request", 180.0,
    "Originator's wait for the responder's next pull request — the "
    "responder ingests the previous page (one tx per page) before "
    "asking again.")
