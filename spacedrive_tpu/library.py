"""Library manager: one SQLite DB + sync manager + config per library.

Mirrors the reference's library subsystem
(/root/reference/core/src/library/manager/mod.rs:138-318 and
library/library.rs:38-60): libraries live under `<data_dir>/libraries/` as
`<uuid>.sdlibrary` JSON configs next to `<uuid>.db` SQLite files; loading
a library builds its Database + SyncManager and registers this node's
instance row; deleting removes both files.
"""

from __future__ import annotations

import json
import os
import time
import uuid as uuidlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from . import persist
from .locations.rules import seed_system_rules
from .store.db import Database, uuid_bytes
from .sync.manager import SyncManager

LIBRARY_CONFIG_VERSION = 1


@dataclass
class LibraryConfig:
    """library/config.rs:28 semantics, JSON-persisted."""

    name: str
    instance_id: str                  # this node's instance pub_id (hex)
    description: str = ""
    version: int = LIBRARY_CONFIG_VERSION

    def to_json(self) -> dict:
        return {"version": self.version, "name": self.name,
                "description": self.description,
                "instance_id": self.instance_id}

    @classmethod
    def from_json(cls, raw: dict) -> "LibraryConfig":
        return cls(name=raw["name"], instance_id=raw["instance_id"],
                   description=raw.get("description", ""),
                   version=raw.get("version", LIBRARY_CONFIG_VERSION))


class Library:
    """The per-library service bundle jobs see as ctx.library."""

    def __init__(self, lib_id: uuidlib.UUID, config: LibraryConfig,
                 db: Database, sync: SyncManager, config_path: str):
        self.id = lib_id
        self.config = config
        self.db = db
        self.sync = sync
        self.config_path = config_path

    @property
    def instance_pub_id(self) -> bytes:
        return bytes.fromhex(self.config.instance_id)

    def save_config(self) -> None:
        persist.atomic_write(
            "library.config", self.config_path,
            json.dumps(self.config.to_json(), indent=2))

    def statistics(self) -> dict:
        """library.statistics procedure data (api/libraries.rs:47)."""
        db = self.db
        objs = db.run("store.object_count")["n"]
        paths = db.run("library.stats.path_count")["n"]
        size_rows = db.run("library.stats.file_sizes")
        total = sum(int.from_bytes(r["size_in_bytes_bytes"] or b"", "big")
                    for r in size_rows)
        unique_rows = db.run("library.stats.unique_sizes")
        unique = sum(int.from_bytes(r["s"] or b"", "big")
                     for r in unique_rows)
        db_size = os.path.getsize(db.path) if os.path.exists(db.path) else 0
        # Persist the LATEST statistics snapshot (single row, replaced in
        # place — a polled query must not grow the table unboundedly).
        with db.write_tx() as conn:
            db.run("library.stats.clear", conn=conn)
            db.run("library.stats.insert",
                   (objs, str(db_size), str(unique), str(total)),
                   conn=conn)
        return {
            "total_object_count": objs,
            "total_path_count": paths,
            "total_bytes_used": str(total),
            "total_unique_bytes": str(unique),
            "library_db_size": str(db_size),
        }


class Libraries:
    """Loads, creates, and deletes libraries (manager/mod.rs:83-318)."""

    def __init__(self, data_dir: str):
        self.dir = os.path.join(data_dir, "libraries")
        os.makedirs(self.dir, exist_ok=True)
        self.libraries: Dict[uuidlib.UUID, Library] = {}
        self._on_event: List[Callable[[str, Library], None]] = []

    def on_event(self, cb: Callable[[str, Library], None]) -> None:
        """Load/Delete hooks (LibraryManagerEvent, manager/mod.rs:43)."""
        self._on_event.append(cb)

    def _emit(self, kind: str, library: Library) -> None:
        for cb in list(self._on_event):
            cb(kind, library)

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        """Load every *.sdlibrary in the data dir (manager/mod.rs:83)."""
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".sdlibrary"):
                continue
            try:
                lib_id = uuidlib.UUID(name[:-len(".sdlibrary")])
            except ValueError:
                continue  # stray non-library file; never block node boot
            if lib_id not in self.libraries:
                self._load(lib_id)

    def create(self, name: str, node_name: str = "node",
               node_pub_id: bytes = b"",
               lib_id: "Optional[uuidlib.UUID]" = None) -> Library:
        """`lib_id` is provided when pairing: a paired library keeps the
        originator's UUID so sync streams address the same library on
        every node (p2p/pairing semantics)."""
        lib_id = lib_id or uuidlib.uuid4()
        instance_pub = uuid_bytes()
        cfg = LibraryConfig(name=name, instance_id=instance_pub.hex())
        cfg_path = os.path.join(self.dir, f"{lib_id}.sdlibrary")
        db = Database(os.path.join(self.dir, f"{lib_id}.db"))
        db.insert("instance", {
            "pub_id": instance_pub, "identity": b"", "node_id": node_pub_id,
            "node_name": node_name, "node_platform": 0,
            "last_seen": int(time.time()), "date_created": int(time.time()),
        })
        seed_system_rules(db)
        sync = SyncManager(db, instance_pub)
        lib = Library(lib_id, cfg, db, sync, cfg_path)
        lib.save_config()
        self.libraries[lib_id] = lib
        self._emit("load", lib)
        return lib

    def _load(self, lib_id: uuidlib.UUID) -> Library:
        cfg_path = os.path.join(self.dir, f"{lib_id}.sdlibrary")
        with open(cfg_path) as f:
            cfg = LibraryConfig.from_json(json.load(f))
        db = Database(os.path.join(self.dir, f"{lib_id}.db"))
        sync = SyncManager(db, bytes.fromhex(cfg.instance_id))
        lib = Library(lib_id, cfg, db, sync, cfg_path)
        self.libraries[lib_id] = lib
        self._emit("load", lib)
        return lib

    def get(self, lib_id: uuidlib.UUID) -> Optional[Library]:
        return self.libraries.get(lib_id)

    def list(self) -> List[Library]:
        return list(self.libraries.values())

    def delete(self, lib_id: uuidlib.UUID) -> None:
        lib = self.libraries.pop(lib_id, None)
        if lib is None:
            raise KeyError(str(lib_id))
        self._emit("delete", lib)
        lib.db.close()
        for suffix in (".sdlibrary", ".db", ".db-wal", ".db-shm"):
            p = os.path.join(self.dir, f"{lib_id}{suffix}")
            if os.path.exists(p):
                os.remove(p)

    def edit(self, lib_id: uuidlib.UUID, name: Optional[str] = None,
             description: Optional[str] = None) -> Library:
        lib = self.libraries[lib_id]
        if name is not None:
            lib.config.name = name
        if description is not None:
            lib.config.description = description
        lib.save_config()
        self._emit("edit", lib)
        return lib
