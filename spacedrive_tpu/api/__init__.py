from .router import Procedure, Router, RpcError, mount_router

__all__ = ["Router", "Procedure", "RpcError", "mount_router"]
