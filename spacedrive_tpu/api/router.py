"""Typed RPC router: the framework's L3 API surface.

Covers the role of the reference's rspc router
(/root/reference/core/src/api/mod.rs:103-200): ~90 procedures in dotted
namespaces, each a query / mutation / subscription, with library-scoped
procedures resolved through middleware
(core/src/api/utils/library.rs semantics: the input carries the library
id, the handler receives the Library). Procedures are plain async
functions; the transport (api/server.py websocket, or direct calls in
tests) is independent of the router, mirroring how rspc mounts under
axum, Tauri IPC, or the React-Native bridge.

Query invalidation (core/src/api/utils/invalidate.rs): mutations declare
which query keys they invalidate; the router emits
CoreEvent::InvalidateOperation on the node event bus after success.
"""

from __future__ import annotations

import asyncio
import inspect
import uuid as uuidlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


class RpcError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


@dataclass
class Procedure:
    name: str
    kind: str                      # query | mutation | subscription
    handler: Callable
    library_scoped: bool
    invalidates: List[str] = field(default_factory=list)


class Router:
    def __init__(self, node):
        self.node = node
        self.procedures: Dict[str, Procedure] = {}
        # Subscriptions live in their OWN namespace (round 15): a path
        # may be both a query (pull the current value) and a
        # subscription (push every change) — `node.health` is both,
        # mirroring rspc where the kinds are separate maps. dispatch()
        # only ever sees `procedures`, subscribe() only this.
        self.subscriptions: Dict[str, Procedure] = {}

    # -- registration ------------------------------------------------------

    def _register(self, name: str, kind: str, library: bool,
                  invalidates: Optional[List[str]] = None):
        def deco(fn):
            registry = self.subscriptions \
                if kind == "subscription" else self.procedures
            assert name not in registry, name
            registry[name] = Procedure(
                name, kind, fn, library, list(invalidates or []))
            return fn
        return deco

    def query(self, name, library=False):
        return self._register(name, "query", library)

    def mutation(self, name, library=False, invalidates=None):
        return self._register(name, "mutation", library, invalidates)

    def subscription(self, name, library=False):
        return self._register(name, "subscription", library)

    # -- dispatch ----------------------------------------------------------

    def _resolve_library(self, input: Any):
        if not isinstance(input, dict) or "library_id" not in input:
            raise RpcError("BAD_REQUEST",
                           "library-scoped procedure needs library_id")
        try:
            lib_id = uuidlib.UUID(str(input["library_id"]))
        except ValueError:
            raise RpcError("BAD_REQUEST", "invalid library_id")
        lib = self.node.libraries.get(lib_id)
        if lib is None:
            raise RpcError("NOT_FOUND", f"library {lib_id} not loaded")
        return lib

    async def dispatch(self, path: str, input: Any = None) -> Any:
        """Run a query or mutation; returns its JSON-safe result."""
        proc = self.procedures.get(path)
        if proc is None:
            if path in self.subscriptions:
                raise RpcError("BAD_REQUEST",
                               f"{path} is a subscription; use "
                               "subscribe()")
            raise RpcError("NOT_FOUND", f"no such procedure: {path}")
        args = [self.node]
        if proc.library_scoped:
            args.append(self._resolve_library(input))
        try:
            result = proc.handler(*args, input)
            if inspect.isawaitable(result):
                result = await result
        except RpcError:
            raise
        except (KeyError, ValueError) as e:
            raise RpcError("BAD_REQUEST", str(e))
        if proc.kind == "mutation" and proc.invalidates:
            lib_id = (input or {}).get("library_id") \
                if isinstance(input, dict) else None
            for key in proc.invalidates:
                self.node.events.invalidate_query(lib_id, key)
        return result

    async def subscribe(self, path: str, input: Any,
                        emit: Callable[[Any], None]) -> Callable[[], None]:
        """Start a subscription; returns an unsubscribe callable."""
        proc = self.subscriptions.get(path)
        if proc is None:
            raise RpcError("NOT_FOUND", f"no such subscription: {path}")
        args = [self.node]
        if proc.library_scoped:
            args.append(self._resolve_library(input))
        result = proc.handler(*args, input, emit)
        if inspect.isawaitable(result):
            result = await result
        return result if callable(result) else (lambda: None)


def mount_router(node) -> Router:
    """Build the full router over a node (api/mod.rs:103-200's mount)."""
    from . import procedures
    router = Router(node)
    procedures.register_all(router)
    # Every `invalidates=` key must name a real query — a typo'd key
    # would silently never refetch (the reference validates invalidation
    # keys against the router at startup, api/utils/invalidate.rs:82).
    for proc in list(router.procedures.values()) \
            + list(router.subscriptions.values()):
        for key in proc.invalidates:
            target = router.procedures.get(key)
            if target is None or target.kind != "query":
                # Hard error (not assert: -O must not disable the guard).
                raise RuntimeError(
                    f"{proc.name} invalidates unknown query {key!r}")
    return router
