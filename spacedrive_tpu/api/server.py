"""HTTP/websocket host: the framework's `apps/server` equivalent.

Mirrors the reference's axum host (/root/reference/apps/server/src/main.rs:40-63):
- `GET /health` — liveness;
- `GET/WS /rspc` — the RPC transport (websocket JSON frames; HTTP GET/POST
  for one-shot queries/mutations);
- `GET /spacedrive/thumbnail/<cas_id>.webp` and
  `GET /spacedrive/file/<library_id>/<location_id>/<file_path_id>` — the
  custom_uri plane (core/src/custom_uri/mod.rs:149-330) serving
  thumbnails and original files with HTTP Range support.

Wire protocol (JSON frames over the websocket):
  → {"id": 1, "type": "query"|"mutation", "path": "...", "input": {...}}
  ← {"id": 1, "type": "response", "result": ...}
  ← {"id": 1, "type": "error", "code": "...", "message": "..."}
  → {"id": 2, "type": "subscription", "path": "...", "input": {...}}
  ← {"id": 2, "type": "event", "data": ...}   (repeatedly)
  → {"id": 2, "type": "subscriptionStop"}
"""

from __future__ import annotations

import asyncio
import json
import mimetypes
import os
from typing import Any, Dict, Optional

from aiohttp import WSMsgType, web

from .. import channels, chaos, tasks, telemetry, threadctx, tracing
from ..locations.paths import IsolatedPath
from ..media.thumbnail import thumbnail_path
from ..telemetry import API_REQUESTS
from ..timeouts import with_timeout
from .router import Router, RpcError, mount_router

RANGE_CHUNK = 1 << 20


class WsSubscriptionPump:
    """One subscription's bounded delivery path: events land in a
    registered `api.ws` channel and ONE supervised drainer task sends
    them under the api.ws.send budget. This is the EventBus's buffered
    edge — in-process subscribers stay synchronous callbacks (cheap
    filters), but delivery to a REMOTE subscriber is where unbounded
    buffering lived: the old shape spawned one emit task per event, so
    a stalled consumer accumulated the node's whole event stream (every
    task parked on its 30s send budget). Now depth is capped by the
    channel contract: TelemetrySnapshot frames coalesce to the newest
    snapshot, and overflow sheds NEW events into
    sd_chan_shed_total{api.ws} — a slow consumer loses events (it was
    going to time out anyway), never wedges the node or its memory."""

    def __init__(self, send, owner: str):
        self._send = send
        self.chan = channels.channel("api.ws")
        self._task = tasks.spawn("ws-pump", self._drain(), owner=owner)

    def offer(self, payload: dict) -> bool:
        """Queue one event frame (loop thread). Returns False when the
        overflow policy shed it."""
        data = payload.get("data")
        key = None
        if isinstance(data, dict) and data.get("type") in (
                "TelemetrySnapshot", "HealthSnapshot",
                "FleetHealthSnapshot"):
            # Snapshot-coalescing (newest wins): only the latest
            # telemetry/health/fleet state matters to a consumer that
            # fell behind — intermediate snapshots are stale by
            # definition.
            key = data["type"]
        return self.chan.put_nowait(payload, key=key)

    async def _drain(self) -> None:
        while True:
            payload = await self.chan.get()
            # Chaos seam: delay = a slow consumer, wedge = a dead one
            # that never reads — the channel above must shed while the
            # drainer is parked (the node and its memory stay bounded;
            # the pump itself is freed by unsubscribe/teardown
            # cancelling this task), drop = a lost frame.
            f = chaos.hit("api.ws.send", only=("delay", "drop", "wedge"))
            if f is not None and await chaos.apply_async(f):
                continue  # dropped
            await self._send(payload)

    async def stop(self) -> None:
        await tasks.cancel_and_gather(self._task)
        # The subscriber is gone: drop its undelivered frames so the
        # per-name depth gauge doesn't freeze at this DEAD instance's
        # depth forever (found by load_bench's wedge gate: a chaos-
        # wedged pump died at full depth and sd_chan_depth{api.ws}
        # read "wedged" long after the consumer was reaped).
        while True:
            try:
                self.chan.get_nowait()
            except asyncio.QueueEmpty:
                break


@web.middleware
async def _count_requests(request: web.Request, handler):
    """Per-route-template request counter (templates, not raw paths, so
    label cardinality stays bounded; unmatched paths share one label)."""
    resource = request.match_info.route.resource  # None for true 404s
    API_REQUESTS.labels(
        route=getattr(resource, "canonical", None) or "unmatched").inc()
    return await handler(request)


class ApiServer:
    def __init__(self, node, router: Optional[Router] = None,
                 http_inflight_cap: Optional[int] = None):
        self.node = node
        self._owner = f"{getattr(node, 'task_owner', 'proc')}/api"
        self.router = router or mount_router(node)
        self.app = web.Application(middlewares=[_count_requests])
        self.app.router.add_get("/", self._index)
        self.app.router.add_get("/static/{name}", self._static)
        self.app.router.add_get("/manifest.webmanifest", self._manifest)
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/rspc", self._rspc_ws)
        self.app.router.add_post("/rspc/{path}", self._rspc_http)
        self.app.router.add_get("/rspc/{path}", self._rspc_http)
        self.app.router.add_get(
            "/spacedrive/thumbnail/{cas_id}.webp", self._thumbnail)
        self.app.router.add_get(
            "/spacedrive/file/{library_id}/{location_id}/{file_path_id}",
            self._file)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None
        # Admission window for rspc HTTP dispatch (declared channel
        # api.http.inflight, policy shed_new): a request past capacity
        # is refused with 503 SHED instead of queueing unbounded
        # behind a saturated backend — the HTTP plane's version of the
        # jobs run-queue's admission refusal. Sheds are the health
        # observatory's named evidence for an API storm.
        # `http_inflight_cap` narrows THIS instance below the declared
        # ceiling (never above) — how the load harness drives the shed
        # edge at bench scale.
        self._inflight = channels.channel(
            "api.http.inflight", capacity_cap=http_inflight_cap)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 8080) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers ----------------------------------------------------------

    async def _health(self, _request: web.Request) -> web.Response:
        return web.Response(text="OK")

    async def _metrics(self, _request: web.Request) -> web.Response:
        """Prometheus text exposition of the node-wide registry — the
        operator-facing face of spacedrive_tpu/telemetry.py (scrape
        this; the webui gets the same data as TelemetrySnapshot
        events)."""
        return web.Response(
            body=telemetry.render_prometheus().encode("utf-8"),
            headers={"Content-Type": telemetry.PROMETHEUS_CONTENT_TYPE})

    async def _index(self, _request: web.Request) -> web.Response:
        """Web explorer entry (apps/web equivalent; assets from
        api/static, the reference's embedded-dist pattern,
        apps/server/src/main.rs:60-63)."""
        from .webui import index_html

        # index_html reads the asset from disk on first render
        html = await asyncio.to_thread(index_html)
        return web.Response(text=html, content_type="text/html")

    async def _static(self, request: web.Request) -> web.Response:
        """Serve the explorer's static assets (no path traversal: the
        name must resolve inside STATIC_DIR)."""
        from .webui import STATIC_DIR

        name = request.match_info["name"]
        path = os.path.realpath(os.path.join(STATIC_DIR, name))
        if not path.startswith(os.path.realpath(STATIC_DIR) + os.sep) \
                or not os.path.isfile(path):
            raise web.HTTPNotFound()
        ctype = (mimetypes.guess_type(path)[0]
                 or "application/octet-stream")
        def _read() -> bytes:
            with open(path, "rb") as f:
                return f.read()

        body = await asyncio.to_thread(_read)
        return web.Response(body=body, content_type=ctype)

    async def _manifest(self, _request: web.Request) -> web.Response:
        """PWA manifest: with the reconnecting websocket client this
        makes the web UI an installable standalone app — the honest
        stand-in for the reference's Tauri desktop shell
        (apps/desktop/src-tauri) in a runtime with no webview toolkit."""
        return web.json_response({
            "name": "Spacedrive TPU",
            "short_name": "sdtpu",
            "start_url": "/",
            "display": "standalone",
            "background_color": "#16161d",
            "theme_color": "#16161d",
            "description": "TPU-native virtual distributed filesystem",
            "icons": [],
        }, content_type="application/manifest+json")

    async def _rspc_http(self, request: web.Request) -> web.Response:
        path = request.match_info["path"]
        if not self._inflight.put_nowait(1):
            # Admission refusal FIRST — before the body read, so a
            # shed request costs zero backend work (a flood of large
            # or slow-trickled bodies must not buy a budgeted read
            # each before being refused). The shed counts into
            # sd_chan_shed_total{api.http.inflight}.
            return web.json_response(
                {"error": {"code": "SHED",
                           "message": "API host at dispatch capacity; "
                                      "retry with backoff"}},
                status=503, headers={"Retry-After": "1"})
        try:
            if request.method == "POST":
                try:
                    # Budgeted body read: a slow-loris client cannot
                    # pin the handler (it occupies its admission slot
                    # for at most the read budget).
                    input = await with_timeout("api.http.read",
                                               request.json())
                except json.JSONDecodeError:
                    input = None
                except asyncio.TimeoutError:
                    # A half-sent body must FAIL the request, not
                    # dispatch the procedure with null input.
                    return web.json_response(
                        {"error": {"code": "TIMEOUT",
                                   "message": "request body read "
                                              "timed out"}},
                        status=408)
            else:
                raw = request.query.get("input")
                input = json.loads(raw) if raw else None
            # Chaos seam (inside the admission window): delay = a slow
            # backend — storms against it drive the shed path above;
            # error = a failing one, reported as 503 so load clients
            # exercise their retry discipline.
            f = chaos.hit("api.http.dispatch", only=("delay", "error"))
            if f is not None:
                try:
                    await chaos.apply_async(f)
                except chaos.ChaosError as e:
                    return web.json_response(
                        {"error": {"code": "UNAVAILABLE",
                                   "message": str(e)}}, status=503)
            # Clients (and the trace_export CLI pulling a live trace)
            # propagate their trace in X-Sdtpu-Trace; the dispatch
            # span then continues it, so an API-triggered sync/job
            # shows up under the caller's id.
            with tracing.continue_trace(
                    request.headers.get("X-Sdtpu-Trace")), \
                    tracing.span(f"rpc/{path}"):
                result = await self.router.dispatch(path, input)
            return web.json_response({"result": result})
        except RpcError as e:
            return web.json_response(
                {"error": {"code": e.code, "message": e.message}},
                status=400 if e.code == "BAD_REQUEST" else 404
                if e.code == "NOT_FOUND" else 500)
        finally:
            try:
                self._inflight.get_nowait()
            except asyncio.QueueEmpty:  # pragma: no cover - defensive
                pass

    async def _rspc_ws(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse()
        await with_timeout("api.ws.prepare", ws.prepare(request))
        subscriptions: Dict[Any, Any] = {}
        loop = asyncio.get_running_loop()

        async def ws_emit(payload: dict) -> None:
            # One subscription event to one subscriber, budgeted; a
            # subscriber that vanished mid-emit (connection gone, send
            # budget fired) is unsubscribe racing us, not an engine
            # error. Anything else — an unserializable payload above
            # all — propagates so the supervisor's done-callback
            # records the task_exception instead of silence.
            try:
                await with_timeout("api.ws.send", ws.send_json(payload))
            except (asyncio.TimeoutError, ConnectionError,
                    RuntimeError):
                # RuntimeError: aiohttp's "websocket connection is
                # closing" shape on a half-closed socket.
                pass

        async def handle(msg: dict) -> None:
            mid = msg.get("id")
            mtype = msg.get("type")
            try:
                if mtype in ("query", "mutation"):
                    try:
                        # The ws envelope's optional "tp" field is the
                        # websocket spelling of X-Sdtpu-Trace.
                        with tracing.continue_trace(msg.get("tp")), \
                                tracing.span(f"rpc/{msg['path']}"):
                            result = await self.router.dispatch(
                                msg["path"], msg.get("input"))
                    except asyncio.TimeoutError as e:
                        # A budget fired INSIDE the procedure (p2p/sync
                        # await): the socket is fine — report it, as
                        # distinct from an api.ws.send wedge below.
                        raise RpcError(
                            "TIMEOUT", f"upstream timeout: {e}") from e
                    await with_timeout("api.ws.send", ws.send_json(
                        {"id": mid, "type": "response", "result": result}))
                elif mtype == "subscription":
                    if mid in subscriptions:
                        # Overwriting the map entry would strand the
                        # prior unsub + pump for the server's lifetime
                        # (nothing would ever tear them down).
                        raise RpcError(
                            "BAD_REQUEST",
                            f"duplicate subscription id {mid!r}")
                    # Bounded per-subscription delivery: events go
                    # through the pump's registered api.ws channel and
                    # one supervised drainer (reaped at shutdown) —
                    # not a task per event.
                    pump = WsSubscriptionPump(ws_emit, owner=self._owner)

                    def emit(data, _mid=mid, _pump=pump):
                        # Thread-safe: event bus callbacks may fire
                        # from worker threads; the channel itself is
                        # loop-thread-only. A loop closed mid-shutdown
                        # drops the frame (counted) instead of
                        # crashing the emitting thread.
                        threadctx.call_threadsafe(
                            loop, _pump.offer,
                            {"id": _mid, "type": "event", "data": data})
                    try:
                        unsub = await self.router.subscribe(
                            msg["path"], msg.get("input"), emit)
                    except asyncio.TimeoutError as e:
                        # Same split as the dispatch branch above: a
                        # budget firing INSIDE the handler is not a
                        # send wedge — the client must hear about it.
                        await pump.stop()
                        raise RpcError(
                            "TIMEOUT", f"upstream timeout: {e}") from e
                    except BaseException:
                        # Failed subscribe (unknown path, bad input):
                        # the pump never reaches the subscriptions map,
                        # so reap its drainer here, then re-raise.
                        await pump.stop()
                        raise
                    subscriptions[mid] = (unsub, pump)
                    await with_timeout("api.ws.send", ws.send_json(
                        {"id": mid, "type": "response", "result": None}))
                elif mtype == "subscriptionStop":
                    unsub, pump = subscriptions.pop(mid, (None, None))
                    if unsub:
                        # Same guard as the disconnect teardown: a
                        # raising unsub must not skip the pump reap
                        # (the entry is already out of the map, so
                        # nothing else would ever stop the drainer).
                        try:
                            unsub()
                        except Exception:
                            pass
                    if pump:
                        await pump.stop()
                else:
                    raise RpcError("BAD_REQUEST",
                                   f"unknown frame type {mtype}")
            except asyncio.TimeoutError:
                # An api.ws.send budget fired: the transport itself is
                # wedged — answering over the same stalled socket would
                # just double the wedge (and report a dispatch error
                # that never happened). Drop the frame; the read side
                # or reap tears the connection down.
                pass
            except RpcError as e:
                await with_timeout("api.ws.send", ws.send_json(
                    {"id": mid, "type": "error",
                     "code": e.code, "message": e.message}))
            except Exception as e:  # noqa: BLE001 — protocol boundary
                await with_timeout("api.ws.send", ws.send_json(
                    {"id": mid, "type": "error",
                     "code": "INTERNAL", "message": str(e)}))

        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    try:
                        frame = json.loads(msg.data)
                    except json.JSONDecodeError:
                        continue
                    await handle(frame)
                elif msg.type == WSMsgType.ERROR:
                    break
        finally:
            # Cancellation-safe teardown: every unsub runs before the
            # first await (an await inside the loop would die on the
            # first CancelledError and strand later subscriptions'
            # EventBus callbacks for the node's lifetime), then ALL
            # pumps reap under one shield whose inner gather is waited
            # out to completion even while the handler itself is being
            # cancelled (same intent as sync_net._pull's ingester reap).
            for unsub, _pump in subscriptions.values():
                try:
                    unsub()
                except Exception:
                    pass
            if subscriptions:
                stops = asyncio.gather(
                    *(pump.stop() for _, pump in subscriptions.values()),
                    return_exceptions=True)
                cancelled = False
                while not stops.done():
                    try:
                        await asyncio.shield(stops)
                    except asyncio.CancelledError:
                        cancelled = True
                if cancelled:
                    raise asyncio.CancelledError
        return ws

    async def _thumbnail(self, request: web.Request) -> web.Response:
        cas_id = request.match_info["cas_id"]
        if not cas_id.isalnum():
            raise web.HTTPBadRequest()
        p = thumbnail_path(self.node.data_dir, cas_id)
        if not os.path.exists(p):
            raise web.HTTPNotFound()
        return web.FileResponse(p, headers={"Content-Type": "image/webp"})

    def _location_is_local(self, lib, loc) -> bool:
        """A location is local when it belongs to this library instance
        (or predates instance attribution)."""
        if loc["instance_id"] is None:
            return True
        row = lib.db.run("node.instance_pub_by_row",
                         (loc["instance_id"],))
        return row is None or row["pub_id"] == lib.sync.instance

    async def _file_over_p2p(self, request, lib, loc, row
                             ) -> web.StreamResponse:
        """Proxy a remote node's file over the p2p mesh
        (custom_uri/mod.rs files_over_p2p_flag path).

        Rows cross the wire as synced pub_ids (local autoincrement ids
        diverge between nodes); the client's Range maps onto the
        spaceblock ranged transfer; the fetched bytes stream back in
        RANGE_CHUNK pieces so multi-GB files never sit in RAM or block
        the event loop."""
        import tempfile

        node = self.node
        if ("filesOverP2P" not in node.config.features
                or node.p2p is None or node.p2p.networked is None):
            raise web.HTTPNotFound()
        inst = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM instance WHERE id = ?", (loc["instance_id"],))
        if inst is None or not inst["identity"]:
            raise web.HTTPNotFound()
        from ..p2p.identity import RemoteIdentity

        route = node.p2p.networked._resolve(RemoteIdentity(inst["identity"]))
        if route is None:
            raise web.HTTPNotFound(text="peer offline")

        range_start = range_end = None
        rng = request.headers.get("Range")
        if rng and rng.startswith("bytes="):
            try:
                start_s, _, end_s = rng[len("bytes="):].partition("-")
                range_start = int(start_s) if start_s else 0
                range_end = int(end_s) + 1 if end_s else None
            except ValueError:
                raise web.HTTPBadRequest()

        with tempfile.NamedTemporaryFile(delete=False) as tmp:
            tmp_path = tmp.name
        try:
            ok = await node.p2p.request_file(
                route[0], route[1], str(lib.id), loc["pub_id"],
                row["pub_id"], tmp_path,
                range_start=range_start, range_end=range_end)
            if not ok:
                raise web.HTTPNotFound(text="remote fetch failed")
            name = (row["name"] or "file") + (
                "." + row["extension"] if row["extension"] else "")
            ctype = mimetypes.guess_type(name)[0] or \
                "application/octet-stream"
            got = os.path.getsize(tmp_path)
            headers = {"Content-Type": ctype, "X-Served-Via": "p2p",
                       "Content-Length": str(got),
                       "Accept-Ranges": "bytes"}
            status = 200
            if range_start is not None:
                end_b = range_start + got - 1
                raw_size = row["size_in_bytes_bytes"]
                size_b = (int.from_bytes(raw_size, "big")
                          if raw_size else "*")
                headers["Content-Range"] = \
                    f"bytes {range_start}-{end_b}/{size_b}"
                status = 206
            resp = web.StreamResponse(status=status, headers=headers)
            await with_timeout("api.http.write", resp.prepare(request))
            with await asyncio.to_thread(open, tmp_path, "rb") as f:
                while True:
                    chunk = await asyncio.to_thread(f.read, RANGE_CHUNK)
                    if not chunk:
                        break
                    # Per-chunk budget: stalled clients release the
                    # handler within one window.
                    await with_timeout("api.http.write", resp.write(chunk))
            await with_timeout("api.http.write", resp.write_eof())
            return resp
        finally:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass

    async def _file(self, request: web.Request) -> web.StreamResponse:
        """Original file serving with Range support
        (custom_uri/mod.rs:149-330)."""
        import uuid as uuidlib
        try:
            lib = self.node.libraries.get(
                uuidlib.UUID(request.match_info["library_id"]))
            location_id = int(request.match_info["location_id"])
            file_path_id = int(request.match_info["file_path_id"])
        except (ValueError, KeyError):
            raise web.HTTPBadRequest()
        if lib is None:
            raise web.HTTPNotFound()
        row = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM file_path WHERE id = ? AND location_id = ?",
            (file_path_id, location_id))
        loc = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM location WHERE id = ?", (location_id,))
        if row is None or loc is None:
            raise web.HTTPNotFound()
        if not await asyncio.to_thread(self._location_is_local, lib, loc):
            # Remote location: proxy the bytes over p2p when the
            # FilesOverP2P feature is on (custom_uri/mod.rs:149-330
            # files_over_p2p_flag path).
            return await self._file_over_p2p(request, lib, loc, row)
        if not loc["path"]:
            raise web.HTTPNotFound()
        iso = IsolatedPath.from_db_row(
            location_id, bool(row["is_dir"]), row["materialized_path"],
            row["name"] or "", row["extension"] or "")
        full = iso.join_on(loc["path"])
        if not os.path.isfile(full):
            raise web.HTTPNotFound()
        size = os.path.getsize(full)
        ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"

        rng = request.headers.get("Range")
        if rng and rng.startswith("bytes="):
            try:
                start_s, _, end_s = rng[len("bytes="):].partition("-")
                start = int(start_s) if start_s else 0
                end = int(end_s) if end_s else size - 1
            except ValueError:
                raise web.HTTPBadRequest()
            end = min(end, size - 1)
            if start > end or start >= size:
                raise web.HTTPRequestRangeNotSatisfiable()
            resp = web.StreamResponse(
                status=206,
                headers={
                    "Content-Type": ctype,
                    "Content-Range": f"bytes {start}-{end}/{size}",
                    "Content-Length": str(end - start + 1),
                    "Accept-Ranges": "bytes",
                })
            await with_timeout("api.http.write", resp.prepare(request))
            with await asyncio.to_thread(open, full, "rb") as f:
                f.seek(start)
                remaining = end - start + 1
                while remaining > 0:
                    chunk = await asyncio.to_thread(
                        f.read, min(RANGE_CHUNK, remaining))
                    if not chunk:
                        break
                    await with_timeout("api.http.write", resp.write(chunk))
                    remaining -= len(chunk)
            await with_timeout("api.http.write", resp.write_eof())
            return resp
        return web.FileResponse(full, headers={
            "Content-Type": ctype, "Accept-Ranges": "bytes"})


async def serve(data_dir: str, host: str = "127.0.0.1",
                port: int = 8080) -> None:
    """CLI entry: run a node + API server until cancelled."""
    from ..node import Node
    node = Node(data_dir)
    await node.start()
    p2p_port = await node.start_p2p()
    node.p2p.interactive_spacedrop = True  # offers flow through p2p.events
    server = ApiServer(node)
    actual = await server.start(host, port)
    print(f"spacedrive_tpu server listening on {host}:{actual} "
          f"(p2p on {p2p_port})")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        # Shielded: serve() exits via cancellation (ctrl-C), and the
        # node must still shut down cleanly through the reap.
        await asyncio.shield(server.stop())
        await asyncio.shield(node.shutdown())
        from ..tracing import stop_profiler

        stop_profiler()  # process exit: flush any SDTPU_PROFILE trace


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    args = ap.parse_args()
    asyncio.run(serve(args.data_dir, args.host, args.port))
