"""JSON-safe row serialization for the API surface."""

from __future__ import annotations

import sqlite3
from typing import Any, Dict, List, Optional


def to_json_value(v: Any) -> Any:
    if isinstance(v, bytes):
        return v.hex()
    return v


def row_to_dict(row) -> Dict[str, Any]:
    d = dict(row) if isinstance(row, sqlite3.Row) else dict(row)
    out = {}
    for k, v in d.items():
        if k == "size_in_bytes_bytes":
            out["size_in_bytes"] = int.from_bytes(v or b"", "big")
        elif k == "inode" and isinstance(v, bytes):
            out[k] = int.from_bytes(v[:8], "big")
        else:
            out[k] = to_json_value(v)
    return out


def rows_to_dicts(rows) -> List[Dict[str, Any]]:
    return [row_to_dict(r) for r in rows]


def file_path_display(row: Dict[str, Any]) -> str:
    ext = row.get("extension") or ""
    return f"{row.get('materialized_path', '/')}{row.get('name', '')}" + \
        (f".{ext}" if ext else "")
