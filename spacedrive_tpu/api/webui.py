"""Embedded web explorer: the L5 surface, grown toward the reference UI.

The reference ships a full React frontend (interface/, 21k LoC) behind
its rspc transport plus a server host that embeds the built assets
(/root/reference/apps/server/src/main.rs:60-63); this framework serves
the same role from real static files — `api/static/{index.html,
style.css, app.js}` — with zero build tooling (plain JS over the live
`/rspc` websocket protocol the TS client generates bindings for).

Views (mirroring interface/app/$libraryId/ routes): overview landing
page (categories + statistics + recents), Explorer grid/list/media
views with inspector, quick-preview overlay, keyboard navigation,
multi-select context menu, drag-and-drop moves, global search, tag
manager, duplicates + near-duplicates, jobs console, P2P panel,
ephemeral browsing, crypto ops, and settings (statistics, volumes,
keys, backups, preferences, notifications).

This module only locates/loads those assets for the server host.
"""

from __future__ import annotations

import os

STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "static")


def asset_path(name: str) -> str:
    return os.path.join(STATIC_DIR, name)


def index_html() -> str:
    with open(asset_path("index.html"), encoding="utf-8") as f:
        return f.read()
