"""Embedded web explorer: the minimal L5 surface.

The reference ships a full React frontend (interface/, 21k LoC) behind
its rspc transport; this framework embeds a single-file explorer served
at `/` by the server host so every core flow is drivable from a browser
with zero build tooling: libraries (list/create), locations (add /
full-rescan), path browsing with thumbnails over the custom_uri routes,
live job progress via the websocket subscription plane, and the dedup
analytics views. The page speaks the same `/rspc` protocol the TS client
of the reference generates bindings for (packages/client).
"""

INDEX_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>spacedrive-tpu</title>
<style>
  :root { color-scheme: dark; }
  body { font: 14px system-ui, sans-serif; margin: 0; background: #16161d;
         color: #e3e3ea; display: flex; height: 100vh; }
  #side { width: 230px; background: #1e1e28; padding: 12px;
          overflow-y: auto; flex-shrink: 0; }
  #main { flex: 1; padding: 16px; overflow-y: auto; }
  h1 { font-size: 15px; margin: 0 0 10px; }
  h2 { font-size: 13px; text-transform: uppercase; color: #8a8a99;
       margin: 14px 0 6px; }
  button { background: #3b82f6; color: white; border: 0; border-radius: 4px;
           padding: 4px 10px; cursor: pointer; margin: 2px 0; }
  button.ghost { background: #2c2c3a; }
  input { background: #12121a; color: #e3e3ea; border: 1px solid #333;
          border-radius: 4px; padding: 4px 6px; }
  .item { padding: 4px 6px; border-radius: 4px; cursor: pointer; }
  .item:hover, .item.sel { background: #2c2c3a; }
  #grid { display: grid; grid-template-columns: repeat(auto-fill, 110px);
          gap: 10px; }
  .cell { width: 110px; text-align: center; }
  .cell .thumb { width: 100px; height: 80px; background: #22222e;
                 border-radius: 6px; display: flex; align-items: center;
                 justify-content: center; margin: 0 auto; overflow: hidden; }
  .cell img { max-width: 100%; max-height: 100%; }
  .cell .nm { font-size: 11px; word-break: break-all; margin-top: 3px; }
  #jobs { position: fixed; bottom: 0; right: 0; width: 320px;
          background: #1e1e28; padding: 8px 12px; border-radius: 8px 0 0 0;
          max-height: 40vh; overflow-y: auto; }
  .job { font-size: 12px; margin: 4px 0; }
  .bar { height: 4px; background: #2c2c3a; border-radius: 2px; }
  .bar > div { height: 4px; background: #3b82f6; border-radius: 2px; }
</style>
</head>
<body>
<div id="side">
  <h1>spacedrive-tpu</h1>
  <h2>Libraries</h2>
  <div id="libs"></div>
  <button id="newlib">+ library</button>
  <h2>Locations</h2>
  <div id="locs"></div>
  <button id="newloc">+ location</button>
</div>
<div id="main">
  <div id="path" style="margin-bottom:10px;color:#8a8a99"></div>
  <div id="grid"></div>
</div>
<div id="jobs"><h2>Jobs</h2><div id="joblist"></div></div>
<script>
let reqId = 0, pending = {}, subs = {};
const wsProto = location.protocol === "https:" ? "wss" : "ws";
const ws = new WebSocket(`${wsProto}://${location.host}/rspc`);
const wsReady = new Promise(res => ws.onopen = res);
ws.onmessage = (m) => {
  const f = JSON.parse(m.data);
  if (f.type === "response" && pending[f.id]) {
    pending[f.id].resolve(f.result); delete pending[f.id];
  } else if (f.type === "error" && pending[f.id]) {
    pending[f.id].reject(new Error(f.message)); delete pending[f.id];
  } else if (f.type === "event" && subs[f.id]) {
    subs[f.id](f.data);
  }
};
async function rpc(type, path, input) {
  await wsReady;
  const id = ++reqId;
  ws.send(JSON.stringify({id, type, path, input}));
  return new Promise((resolve, reject) => pending[id] = {resolve, reject});
}
const q = (p, i) => rpc("query", p, i);
const mut = (p, i) => rpc("mutation", p, i);
async function sub(path, input, cb) {
  await wsReady;
  const id = ++reqId;
  subs[id] = cb;
  ws.send(JSON.stringify({id, type: "subscription", path, input}));
}

let lib = null, loc = null, curPath = "/";
async function loadLibs() {
  const libs = await q("library.list");
  const el = document.getElementById("libs"); el.innerHTML = "";
  for (const l of libs) {
    const d = document.createElement("div");
    d.className = "item" + (lib === l.uuid ? " sel" : "");
    d.textContent = l.config ? l.config.name : l.name;
    d.onclick = () => { lib = l.uuid; loadLibs(); loadLocs(); };
    el.appendChild(d);
  }
  if (!lib && libs.length) { lib = libs[0].uuid; loadLocs(); }
}
async function loadLocs() {
  if (!lib) return;
  const locs = await q("locations.list", {library_id: lib});
  const el = document.getElementById("locs"); el.innerHTML = "";
  for (const l of locs) {
    const d = document.createElement("div");
    d.className = "item" + (loc === l.id ? " sel" : "");
    d.textContent = l.name || l.path;
    d.oncontextmenu = async (e) => {
      e.preventDefault();
      await mut("locations.fullRescan", {library_id: lib, location_id: l.id});
    };
    d.onclick = () => { loc = l.id; curPath = "/"; browse(); loadLocs(); };
    el.appendChild(d);
  }
}
async function browse() {
  if (!lib || loc == null) return;
  document.getElementById("path").textContent = `location ${loc} · ${curPath}`;
  const rows = await q("search.paths", {
    library_id: lib, take: 400,
    filter: {location_id: loc, materialized_path: curPath},
  });
  const grid = document.getElementById("grid"); grid.innerHTML = "";
  if (curPath !== "/") {
    grid.appendChild(cell("..", null, true, () => {
      curPath = curPath.replace(/[^/]+\/$/, ""); browse();
    }));
  }
  for (const r of (rows.items || rows)) {
    const isDir = !!r.is_dir;
    const name = r.name + (r.extension ? "." + r.extension : "");
    grid.appendChild(cell(name, r.cas_id, isDir, () => {
      if (isDir) { curPath = r.materialized_path + r.name + "/"; browse(); }
    }));
  }
}
function cell(name, cas, isDir, onclick) {
  const c = document.createElement("div"); c.className = "cell";
  const t = document.createElement("div"); t.className = "thumb";
  if (cas) {
    const img = document.createElement("img");
    img.src = `/spacedrive/thumbnail/${cas}.webp`;
    img.onerror = () => { img.remove(); t.textContent = "🗎"; };
    t.appendChild(img);
  } else t.textContent = isDir ? "📁" : "🗎";
  const n = document.createElement("div"); n.className = "nm";
  n.textContent = name;
  c.appendChild(t); c.appendChild(n);
  c.onclick = onclick;
  return c;
}
document.getElementById("newlib").onclick = async () => {
  const name = prompt("library name"); if (!name) return;
  await mut("library.create", {name}); lib = null; loadLibs();
};
document.getElementById("newloc").onclick = async () => {
  const path = prompt("absolute path to index"); if (!path || !lib) return;
  await mut("locations.create", {library_id: lib, path});
  loadLocs();
};
sub("jobs.progress", null, (e) => {
  const el = document.getElementById("joblist");
  let row = document.getElementById("job-" + e.id);
  if (!row) {
    row = document.createElement("div"); row.className = "job";
    row.id = "job-" + e.id;
    row.innerHTML = `<span></span><div class="bar"><div></div></div>`;
    el.prepend(row);
  }
  row.querySelector("span").textContent =
    `${e.name || "job"} — ${e.message || ""}`;
  const pct = e.task_count ? (100 * (e.completed_task_count || 0) /
                              e.task_count) : 0;
  row.querySelector(".bar > div").style.width = pct + "%";
  if (e.task_count && e.completed_task_count >= e.task_count)
    setTimeout(() => row.remove(), 4000);
});
sub("invalidation.listen", null, (e) => {
  if (e.key === "search.paths") browse();
  if (e.key === "library.list") loadLibs();
});
sub("p2p.events", null, async (e) => {
  if (e.type === "SpacedropRequest") {
    // The peer-supplied name is untrusted: suggest only its basename,
    // never a path ("../../etc/x" must not prefill the save prompt).
    const safe = (e.name || "spacedrop.bin")
      .split(/[\\/]/).pop().replace(/^\.+/, "") || "spacedrop.bin";
    const ok = confirm(
      `Spacedrop: accept "${safe}" (${e.size} bytes) from ${e.peer}?`);
    // Cancelling/clearing the prompt falls back to the safe name in the
    // current directory — an accepted drop is never silently rejected.
    const path = ok ? (prompt("save as", safe) || safe) : null;
    await mut("p2p.acceptSpacedrop", {id: e.id, path});
  }
});
loadLibs();
</script>
</body>
</html>
"""
